"""The versioned K/V hand-off contract (docs/DESIGN.md §5n).

One wire format for every boundary a request's paged K/V crosses a
process or engine edge on: the PR 15 disk spill tier (preempt to disk,
crash restore), cross-engine migration, and the disaggregated
prefill→decode hand-off.  The format is the former ad-hoc
``<spill_dir>/<rid>.npz`` promoted to a contract:

====================  =================================================
bytes                 field
====================  =================================================
``[0, 4)``            magic ``b"PTKV"``
``[4, 8)``            format version, u32 little-endian (currently 1)
``[8, 16)``           JSON header length, u64 little-endian
``[16, 16+hlen)``     UTF-8 JSON header: ``{"fingerprint": <the
                      writing pool's full config_fingerprint()>,
                      "meta": <spill meta — rid, prompt_len,
                      committed, written, block_size, layers, fields,
                      cache_dtype>, "arrays": [{name, dtype, shape,
                      offset, nbytes}, ...]}``
``[data_start, ...)`` raw C-order array blobs; ``data_start`` is
                      ``16+hlen`` rounded up to 64, each array's
                      ``offset`` is relative to ``data_start`` and
                      64-aligned
====================  =================================================

Why this shape: the header is self-describing (a reader needs nothing
but this table), the version check is a 16-byte read, and the 64-byte
alignment means :class:`TransferReader` can hand out zero-copy
``np.frombuffer`` views over one ``mmap`` — a same-host adopt never
copies K/V through Python; the only copies are the device uploads
``_resume`` was already doing.

The writer keeps the PR 15 durability discipline unchanged: tmp file +
flush + fsync + atomic ``os.replace``, one transient retry at the fault
seam (``spill.write`` for preemption spills, ``xfer.write`` for
disaggregation exports), a ``<seam>.error`` trace event per caught
fault so chaos harnesses reconcile injections against the recorder, and
tmp-file cleanup on the persistent failure path.

The typed errors subclass ``InvalidArgumentError`` so
``faults.classify_error`` calls them PERMANENT — a stale-version or
alien-fingerprint file is never retried, the adopting engine falls back
to prompt+committed resubmit (which is always available and always
byte-identical under greedy decoding).
"""
from __future__ import annotations

import json
import mmap
import os
import struct
from typing import Dict, Optional

import numpy as np

from ..core.errors import InvalidArgumentError
from . import faults, trace

__all__ = ["MAGIC", "VERSION", "CAPACITY_KEYS",
           "TransferFormatError", "TransferVersionError",
           "TransferFingerprintError",
           "write_transfer", "TransferReader", "check_fingerprint"]

MAGIC = b"PTKV"
VERSION = 1

# fingerprint keys a hand-off is allowed to differ on: tier capacity is
# a per-engine deployment choice (a prefill tier sized for admission
# and a decode tier sized for residency SHOULD differ here), while
# everything else — sampling config, cache layout/dtype/geometry —
# changes bytes and must match exactly
CAPACITY_KEYS = frozenset({"slots", "num_blocks", "mesh"})

_HEADER_STRUCT = struct.Struct("<4sIQ")  # magic, version, header length
_ALIGN = 64


class TransferFormatError(InvalidArgumentError):
    """The file is not a PTKV transfer at all — wrong magic, truncated
    prefix, or unparsable header.  ``legacy_npz`` is True when the
    magic is a zip local-file header (``PK\\x03\\x04``): a pre-upgrade
    engine's unversioned ``np.savez`` spill, which adopters reject with
    a one-line log instead of a crash (and leave on disk — it is the
    old engine's to clean up)."""

    def __init__(self, msg: str, legacy_npz: bool = False):
        super().__init__(msg)
        self.legacy_npz = legacy_npz


class TransferVersionError(InvalidArgumentError):
    """The file IS a PTKV transfer, but written under a different
    format version than this reader speaks.  Carries ``found`` so the
    adopter can apply the staleness rule: ``found < VERSION`` is a
    pre-upgrade leftover under OUR naming scheme — delete it (the PR 15
    stale-file rule: a file that can never be adopted again is litter);
    ``found > VERSION`` is a NEWER engine's file — leave it alone."""

    def __init__(self, msg: str, found: int):
        super().__init__(msg)
        self.found = int(found)


class TransferFingerprintError(InvalidArgumentError):
    """The writer's config fingerprint disagrees with the reader's on a
    byte-identity-relevant key (anything outside :data:`CAPACITY_KEYS`).
    Adopting would replay under different sampling/cache semantics —
    the file is another deployment's, so the adopter falls back WITHOUT
    deleting what is not its to judge.  ``keys`` names the differing
    fingerprint keys, both values in the message."""

    def __init__(self, msg: str, keys):
        super().__init__(msg)
        self.keys = tuple(keys)


def _align(n: int) -> int:
    return -(-n // _ALIGN) * _ALIGN


def write_transfer(path: str, fingerprint: dict, meta: dict,
                   arrays: Dict[str, np.ndarray],
                   seam: str = "xfer.write", rid=None) -> str:
    """Serialize ``arrays`` under the PTKV contract to ``path``.

    Durability and fault semantics are the spill writer's, verbatim:
    the whole image is built in memory first, the ``seam`` fault point
    fires before any I/O, the bytes go to ``path + ".tmp"`` and are
    fsynced before the atomic ``os.replace`` — a crash mid-write can
    never leave a half file an adopting engine would read.  A transient
    failure (fault classification, docs §5f) is retried ONCE; each
    caught fault emits a ``<seam-group>.error`` trace event
    (``spill.error`` / ``xfer.error``) naming the rid, error type, and
    whether a retry follows; a persistent failure removes the tmp file
    and propagates to the caller, which leaves the pool untouched."""
    table = []
    blobs = []
    offset = 0
    for name, arr in arrays.items():
        arr = np.ascontiguousarray(arr)
        table.append({"name": str(name), "dtype": str(arr.dtype),
                      "shape": list(arr.shape), "offset": offset,
                      "nbytes": int(arr.nbytes)})
        blobs.append(arr)
        offset = _align(offset + arr.nbytes)
    header = json.dumps({"fingerprint": fingerprint, "meta": meta,
                         "arrays": table},
                        sort_keys=True).encode("utf-8")
    prefix = _HEADER_STRUCT.pack(MAGIC, VERSION, len(header))
    data_start = _align(len(prefix) + len(header))
    image = bytearray(data_start + (_align(offset) if blobs else 0))
    image[:len(prefix)] = prefix
    image[len(prefix):len(prefix) + len(header)] = header
    for entry, arr in zip(table, blobs):
        lo = data_start + entry["offset"]
        image[lo:lo + entry["nbytes"]] = arr.tobytes()
    event = seam.split(".", 1)[0] + ".error"
    tmp = path + ".tmp"
    for attempt in (0, 1):
        try:
            faults.fire(seam)
            with open(tmp, "wb") as f:
                f.write(image)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
            return path
        except BaseException as e:  # noqa: BLE001 - classify + retry
            retry = attempt == 0 \
                and faults.classify_error(e) == "transient"
            tr = trace.active()
            if tr is not None:
                tr.instant(event, rid=rid, error=type(e).__name__,
                           retried=retry)
            if not retry:
                # a persistently failed write must not leave its
                # half-written .tmp littering the transfer dir
                try:
                    os.remove(tmp)
                except OSError:
                    pass
                raise
    raise AssertionError("unreachable")  # pragma: no cover


class TransferReader:
    """mmap-backed zero-copy reader for one PTKV transfer file.

    ``arrays`` are read-only ``np.frombuffer`` views over the mapping —
    the kernel pages K/V in on first touch and the bytes never transit
    a Python-level copy; the device upload in ``_resume`` (a fancy-
    indexed ``.at[].set``) is the first and only copy.  Keep the reader
    open while the views are live; :meth:`close` (or the context
    manager exit) invalidates them.

    Raises :class:`TransferFormatError` (bad/legacy magic, truncated or
    corrupt header) or :class:`TransferVersionError` (right magic,
    wrong version) — both permanent by classification."""

    def __init__(self, path: str):
        self.path = path
        f = open(path, "rb")
        try:
            head = f.read(_HEADER_STRUCT.size)
            if len(head) < _HEADER_STRUCT.size \
                    or head[:4] != MAGIC:
                legacy = head[:4] == b"PK\x03\x04"
                raise TransferFormatError(
                    "%s is not a PTKV transfer file (magic %r)%s"
                    % (path, bytes(head[:4]),
                       " — pre-upgrade unversioned .npz spill"
                       if legacy else ""),
                    legacy_npz=legacy)
            _, version, hlen = _HEADER_STRUCT.unpack(head)
            if version != VERSION:
                raise TransferVersionError(
                    "%s is PTKV format version %d; this engine speaks "
                    "version %d" % (path, version, VERSION), version)
            size = os.fstat(f.fileno()).st_size
            data_start = _align(_HEADER_STRUCT.size + hlen)
            if size < data_start:
                raise TransferFormatError(
                    "%s truncated: %d bytes < header end %d"
                    % (path, size, data_start))
            try:
                header = json.loads(
                    f.read(hlen).decode("utf-8"))
            except (UnicodeDecodeError, ValueError) as e:
                raise TransferFormatError(
                    "%s header is not valid JSON: %s" % (path, e))
            self._mm = mmap.mmap(f.fileno(), 0,
                                 access=mmap.ACCESS_READ)
        finally:
            f.close()
        self.fingerprint = header.get("fingerprint") or {}
        self.meta = header.get("meta") or {}
        self.arrays: Dict[str, np.ndarray] = {}
        self.nbytes = 0
        for entry in header.get("arrays") or ():
            lo = data_start + int(entry["offset"])
            hi = lo + int(entry["nbytes"])
            if hi > size:
                raise TransferFormatError(
                    "%s truncated: array %r wants bytes [%d, %d) of a "
                    "%d-byte file" % (path, entry["name"], lo, hi,
                                      size))
            view = np.frombuffer(
                self._mm, dtype=np.dtype(entry["dtype"]),
                count=int(np.prod(entry["shape"], dtype=np.int64)),
                offset=lo).reshape(entry["shape"])
            self.arrays[entry["name"]] = view
            self.nbytes += int(entry["nbytes"])

    def close(self) -> None:
        if getattr(self, "_mm", None) is not None:
            # drop the views first: closing a mapping with exported
            # buffers raises on CPython
            self.arrays = {k: np.array(v)
                           for k, v in self.arrays.items()}
            self._mm.close()
            self._mm = None

    def __enter__(self) -> "TransferReader":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def check_fingerprint(header_fp: dict, pool_fp: dict) -> None:
    """Raise :class:`TransferFingerprintError` when the writer's and
    reader's fingerprints differ on any key OUTSIDE
    :data:`CAPACITY_KEYS` — the disaggregation rule: a prefill tier
    and a decode tier legitimately differ in slots/blocks/mesh (tier
    sizing is the point), but sampling and cache semantics must match
    or the adopted K/V replays under different numerics."""
    keys = (set(header_fp) | set(pool_fp)) - CAPACITY_KEYS
    bad = sorted(k for k in keys
                 if header_fp.get(k) != pool_fp.get(k))
    if bad:
        raise TransferFingerprintError(
            "transfer fingerprint disagrees on %s: file has %s, pool "
            "has %s" % (bad,
                        {k: header_fp.get(k) for k in bad},
                        {k: pool_fp.get(k) for k in bad}), bad)
