"""Serving observability: counters, gauges, histograms, exposition.

A deliberately small registry — no labels, no metric vectors, no
background collection — because the engine records everything from the
REAL code path: admission increments the counters inside ``submit()``,
TTFT is observed by the pool's ``on_token`` hook the moment the prefill
emits a request's first token, the robustness counters
(``serving_requests_recovered_total``, ``serving_recoveries_total``,
``serving_requests_shed_total``, ``serving_engine_restarts_total``,
``serving_ticks_stalled_total``) increment inside the recovery /
shedding / watchdog paths themselves (docs/DESIGN.md §5f), the
scheduling surface (``serving_preemptions_total``,
``serving_resumes_total``, ``serving_spill_bytes_total``,
``serving_admission_tightened_total``, plus the
``serving_preempted_requests`` / ``serving_spilled_blocks`` /
``serving_degrade_level`` gauges) increments inside the preempt /
resume / degradation-ladder decisions (docs/DESIGN.md §5j), the
crash-durability surface (``serving_journal_records_total`` /
``serving_journal_bytes_total`` / ``serving_journal_errors_total`` /
``serving_journal_truncated_records_total`` /
``serving_checkpoints_total`` / ``serving_journal_replayed_total`` /
``serving_restores_total``) increments inside the journal append /
flush / checkpoint / restore paths themselves — the replayed counter
reconciles EXACTLY with the journal's admitted-minus-terminal records
(docs/DESIGN.md §5m) — and KV-cache gauges read
``cache_stats()`` (the allocator's own accounting) after every step —
``serving_kv_reachable_bytes`` (what a step can READ right now) and
``serving_kv_resident_bytes`` (the whole pool allocation), both
dtype-aware: an int8 quantized cache reports int8 K/V bytes plus the
riding fp32 per-head scales, so the ~4x byte reduction vs fp32 shows up
on the dashboard, not just in prose.
``snapshot()`` returns plain python for tests/JSON; the text exposition
(``render_prometheus``) follows the Prometheus conventions (counters
end in ``_total``, histograms emit cumulative ``_bucket{le=...}`` plus
``_sum``/``_count``) so a scrape endpoint is one HTTP handler away.
"""
from __future__ import annotations

import bisect
import re
from typing import Dict, Optional, Sequence

from ..core.errors import InvalidArgumentError

__all__ = ["Counter", "Gauge", "Histogram", "MetricsRegistry",
           "DEFAULT_TIME_BUCKETS", "escape_help", "escape_label_value"]

# latency buckets spanning sub-millisecond CPU test steps to the
# multi-second TTFTs of a cold bucket compile on a loaded server
DEFAULT_TIME_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                        0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0)

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")


def _fmt(v: float) -> str:
    return "%.10g" % float(v)


def escape_help(s: str) -> str:
    """Prometheus text-format HELP escaping: ``\\`` and newline (a raw
    newline would split one HELP across two exposition lines, breaking
    the scrape; the format spec says escape exactly these two)."""
    return str(s).replace("\\", "\\\\").replace("\n", "\\n")


def escape_label_value(s: str) -> str:
    """Prometheus label-value escaping: ``\\``, newline, and ``"`` (the
    value is double-quoted in the exposition, so an unescaped quote
    truncates it mid-value)."""
    return str(s).replace("\\", "\\\\").replace("\n", "\\n") \
        .replace('"', '\\"')


class _Metric:
    kind = "untyped"

    def __init__(self, name: str, help: str = ""):
        if not _NAME_RE.match(name):
            raise InvalidArgumentError(
                "metric name %r is not a valid prometheus identifier "
                "([a-zA-Z_:][a-zA-Z0-9_:]*)" % (name,))
        self.name = name
        self.help = help


class Counter(_Metric):
    """Monotonic count (requests, tokens, rejections)."""

    kind = "counter"

    def __init__(self, name: str, help: str = ""):
        super().__init__(name, help)
        self.value = 0.0

    def inc(self, n: float = 1.0) -> None:
        if n < 0:
            raise InvalidArgumentError(
                "counter %s only goes up (inc %r); use a Gauge for "
                "values that fall" % (self.name, n))
        self.value += n

    def snapshot(self):
        return self.value


class Gauge(_Metric):
    """Point-in-time value (queue depth, slot occupancy, tokens/s)."""

    kind = "gauge"

    def __init__(self, name: str, help: str = ""):
        super().__init__(name, help)
        self.value = 0.0

    def set(self, v: float) -> None:
        self.value = float(v)

    def snapshot(self):
        return self.value


class Histogram(_Metric):
    """Fixed-bucket distribution (TTFT, inter-token latency).

    Buckets are upper bounds (prometheus ``le`` semantics); an
    observation lands in the first bucket whose bound >= value, or the
    implicit ``+Inf`` overflow.  ``quantile(q)`` returns the upper
    bound of the bucket containing the q-quantile — an upper ESTIMATE,
    the histogram_quantile convention, exact only in distribution."""

    kind = "histogram"

    def __init__(self, name: str, help: str = "",
                 buckets: Sequence[float] = DEFAULT_TIME_BUCKETS):
        super().__init__(name, help)
        bs = tuple(float(b) for b in buckets)
        if not bs or any(b2 <= b1 for b1, b2 in zip(bs, bs[1:])):
            raise InvalidArgumentError(
                "histogram %s buckets must be non-empty and strictly "
                "increasing, got %r" % (name, buckets))
        self.buckets = bs
        self._counts = [0] * (len(bs) + 1)  # last = +Inf overflow
        self.count = 0
        self.sum = 0.0

    def observe(self, v: float) -> None:
        v = float(v)
        self.count += 1
        self.sum += v
        self._counts[bisect.bisect_left(self.buckets, v)] += 1

    def reset(self) -> None:
        """Zero all counts, keeping the bucket layout.  For callers that
        warm a code path (compile, cache fill) before the measurement
        window and must not let the warmup observations pollute
        engine-lifetime quantiles."""
        self._counts = [0] * (len(self.buckets) + 1)
        self.count = 0
        self.sum = 0.0

    def quantile(self, q: float) -> Optional[float]:
        if not 0.0 <= q <= 1.0:
            raise InvalidArgumentError(
                "quantile must be in [0, 1], got %r" % (q,))
        if not self.count:
            return None
        target = q * self.count
        running = 0
        for i, c in enumerate(self._counts):
            running += c
            if running and running >= target:
                return (self.buckets[i] if i < len(self.buckets)
                        else float("inf"))
        return float("inf")

    def snapshot(self):
        cum: Dict[str, int] = {}
        running = 0
        for b, c in zip(self.buckets, self._counts):
            running += c
            cum[_fmt(b)] = running
        cum["+Inf"] = self.count
        return {"count": self.count, "sum": self.sum, "buckets": cum}


class MetricsRegistry:
    """Create-or-get registry of named metrics.

    ``counter``/``gauge``/``histogram`` return the existing metric when
    the name is already registered (so engine restarts over a shared
    registry accumulate instead of clobbering) and refuse a name
    registered under a different type."""

    def __init__(self):
        self._metrics: Dict[str, _Metric] = {}

    def _get(self, cls, name: str, help: str, **kwargs):
        m = self._metrics.get(name)
        if m is not None:
            if type(m) is not cls:
                raise InvalidArgumentError(
                    "metric %r is already registered as a %s, not a %s"
                    % (name, m.kind, cls.kind))
            want = kwargs.get("buckets")
            if want is not None and \
                    tuple(float(b) for b in want) != m.buckets:
                # returning the old histogram would silently mis-bucket
                # the new caller's observations
                raise InvalidArgumentError(
                    "histogram %r is already registered with buckets %s "
                    "(requested %s)" % (name, m.buckets, tuple(want)))
            return m
        m = cls(name, help, **kwargs)
        self._metrics[name] = m
        return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get(Counter, name, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get(Gauge, name, help)

    def histogram(self, name: str, help: str = "",
                  buckets: Sequence[float] = DEFAULT_TIME_BUCKETS
                  ) -> Histogram:
        return self._get(Histogram, name, help, buckets=buckets)

    def reset_all(self) -> None:
        """Zero every registered metric IN PLACE — counters and gauges
        to 0, histogram counts cleared — keeping the registrations,
        bucket layouts, and metric object identities (the engine holds
        direct references).  Test isolation for suites sharing one
        registry/engine, and the warm-outside-the-timed-region
        discipline bench legs apply per-histogram, available for a whole
        registry at once."""
        for m in self._metrics.values():
            if isinstance(m, Histogram):
                m.reset()
            else:
                m.value = 0.0

    def snapshot(self) -> dict:
        """{name: value | {count, sum, buckets}} — plain python, JSON
        and test friendly."""
        return {name: m.snapshot() for name, m in self._metrics.items()}

    def render_prometheus(self) -> str:
        """Text exposition format (one scrape body).  HELP strings and
        label values are escaped per the format spec (``\\``/newline,
        plus ``"`` in label values) — a metric whose help text quotes an
        error message must not be able to corrupt the whole scrape."""
        lines = []
        for m in self._metrics.values():
            if m.help:
                lines.append("# HELP %s %s"
                             % (m.name, escape_help(m.help)))
            lines.append("# TYPE %s %s" % (m.name, m.kind))
            if isinstance(m, Histogram):
                running = 0
                for b, c in zip(m.buckets, m._counts):
                    running += c
                    lines.append('%s_bucket{le="%s"} %d'
                                 % (m.name,
                                    escape_label_value(_fmt(b)),
                                    running))
                lines.append('%s_bucket{le="+Inf"} %d'
                             % (m.name, m.count))
                lines.append("%s_sum %s" % (m.name, _fmt(m.sum)))
                lines.append("%s_count %d" % (m.name, m.count))
            else:
                lines.append("%s %s" % (m.name, _fmt(m.value)))
        return "\n".join(lines) + "\n"
