"""``paddle_tpu.serving`` — the request scheduler over the decode engine.

The serving stack, bottom to top (docs/DESIGN.md §5a-§5c):

- ``jit.DecodeSession`` — exactly-two-compiles prefill/decode split;
- ``inference.GenerationPool`` — slot-based continuous batching, paged
  KV with a free-list allocator;
- **this package** — the entry point the ROADMAP north star needs:
  request lifecycle + streaming (``ServingEngine.submit`` →
  ``ResponseStream``), per-request deadlines, bounded-queue admission
  control (typed, retryable ``QueueFullError``), mid-generation
  cancellation that frees slots and paged blocks, graceful
  drain/shutdown, hot weight swap, and a serving metrics registry
  (TTFT, inter-token latency, queue depth, occupancy, tokens/s) with
  prometheus text exposition.

Fault tolerance (docs/DESIGN.md §5f): a failed ``pool.step()`` has a
REQUEST-level blast radius — the engine rebuilds the pool and resubmits
each victim's prompt+committed tokens, so greedy survivors continue
token-identically, with typed transient-vs-permanent classification and
a bounded per-request retry budget.  ``faults`` is the deterministic
injection plane (named seams, scripted schedules, seeded chaos mode —
a module-level no-op when off); ``Supervisor`` is the watchdog that
restarts a dead loop and flags wedged ticks; ``ServingEngine.health()``
backs ``GET /healthz``; deadline-aware admission sheds unattainable
requests with the retryable ``DeadlineUnattainableError``.

Observability (docs/DESIGN.md §5g): ``metrics`` is the aggregate
surface, ``supervisor`` the liveness surface, and ``trace`` the
request-scoped one — a bounded flight recorder plus span/event tracing
of the full request path (lifecycle transitions, tick phases, compile
events, fault injections, recoveries, sheds, restarts), a module-level
no-op when off, with an opt-in deep-timing mode that syncs phase edges
for honest device attribution.  Export via
``ServingEngine.export_chrome_trace()`` (Chrome/Perfetto JSON),
``GET /debug/trace?rid=<id>`` / ``GET /debug/flightrec`` on the HTTP
front end, and automatic post-mortem dumps into ``EngineHealth`` when
supervision trips.

The observatory (docs/DESIGN.md §5h): ``ServingEngine.cost_report()``
reads XLA's cost/memory analyses off the AOT-compiled decode
executables (per-token FLOPs/bytes, HBM reservation, cache footprint
reconciled against ``kv_reachable_bytes``), ``slo`` tracks declarative
objectives with fast/slow burn-rate alerting (``GET /slo``, folded
into ``health()``), and ``log`` emits structured JSON lines at the
admission/terminal/recovery/shed/restart edges — both planes
module-level no-ops when unconfigured.

Real traffic shapes (docs/DESIGN.md §5i): paged pools take
``prefill_chunk_tokens=`` (bounded chunked prefill interleaved with
decode — a long prompt can no longer blow resident requests'
inter-token p95) and ``prefix_sharing=True`` (refcounted blocks + a
chain-hashed prefix index: admission maps a resident shared prefix
read-only and prefills only the suffix, byte-identical to sharing-off)
— surfaced as ``serving_prefix_hit_rate`` /
``serving_prefix_blocks_shared`` / ``serving_prefill_chunks_total``
and the ``prefix_hit_tokens`` stamp on ``req.admitted`` log lines.

Traffic-grade scheduling (docs/DESIGN.md §5j): requests carry
``priority`` classes (``PRIORITY_CLASSES`` or any int) and optional
``tenant`` fairness keys; admission is (priority, deadline, arrival)-
ordered with per-tenant slot caps, and ``ServingEngine.preempt()``
evicts a decoding victim by spilling its paged K/V (int8 scales
included) to a host-RAM block tier — resumed BYTE-identically with no
new compiles.  ``degrade=True`` closes the loop on the SLO plane: the
multi-window burn alert drives a ladder (preempt low-priority → reduce
spec-K → tighten admission, ``AdmissionTightenedError`` at the door)
that steps down while the alert burns and back up as it clears, every
decision emitted as ``sched.preempt``/``sched.resume``/
``sched.degrade``/``sched.restore`` flight-recorder events and
structured-log lines.  A degraded engine is HEALTHY: ``GET /healthz``
stays 200 and carries the level.

Sharded serving (docs/DESIGN.md §5k): ``ServingEngine(...,
mesh=jit.mesh.DecodeMesh(dp, mp))`` runs the SAME scheduler over a
GSPMD decode pool — the slot axis and paged block pool sharded over
``dp`` (per-shard scratch/free-list partition), attention heads and
MLP hidden over ``mp`` — byte-identical to the unsharded engine with
unchanged compile counts.  The engine sees logical slots only; mesh
engines additionally export ``serving_mesh_devices`` and the per-shard
KV byte gauges (per-chip headroom, not mesh-total optimism).

Crash-durable serving (docs/DESIGN.md §5m): ``journal`` is the
append-only, CRC-framed write-ahead request journal —
``ServingEngine(journal_path=...)`` records admissions (with the
pool's sampling/cache config fingerprint in the header) and per-tick
committed-token batches, ``checkpoint()`` compacts, and
``restore(path)`` lets a FRESH process (or a second engine with the
same weights) adopt the journal plus the ``spill_tier="disk"``
directory and finish every greedy survivor byte-identically with zero
new compiles — torn tails truncate (never crash), fingerprint
mismatches raise typed errors naming both sides, and the RESTORING
state answers ``/healthz`` 503 + Retry-After while deferring (never
dropping) admissions.  ``journal.append``/``spill.write`` are fault
seams, and the ``serving_restart`` bench leg stamps the measured RTO
with ``tokens_lost == 0`` required for promotion.

Disaggregated serving (docs/DESIGN.md §5n): ``transfer`` is the
versioned K/V hand-off contract — a magic+version+fingerprint-headered,
64-byte-aligned, fsync'd single file (``write_transfer`` /
``TransferReader``) that the disk spill tier, crash restore and tier
hand-off all share — and ``DisaggregatedServing`` runs a prefill-role
engine (admission + chunked prefill, exports at first token over the
``xfer.write`` seam) next to a decode-role engine (adopts via the
PR 15 upload path, never compiles a prefill-chunk executable) behind
one fused-looking front: one stream per request across the hand-off,
byte-identical to the fused engine, deadline shed that includes the
observed mean ``serving_handoff_wait_s``, and
``serving_kv_transfers_total`` / ``serving_kv_transfer_bytes_total``
on the front's registry.  Stale-version files are deleted (resubmit
fallback covers them), alien-fingerprint and pre-upgrade unversioned
files are left alone and logged — never adopted, never crash.

The serving fleet (docs/DESIGN.md §5o): ``ServingFleet`` fronts N
fused engines with the single-engine API — prefix-affinity routing
(the router replays the pool's chain-hash prefix walk against each
engine's epoch-cached ``resident_prefix_digest()`` so shared-prefix
traffic lands where its blocks already live, falling back to
least-loaded placement scored from ``health()`` backpressure), LIVE
request migration (``retire_engine`` preempts victims to their disk
transfer files, ``GenerationPool.detach_spilled`` releases the file
for ``adopt_migration`` on a peer — zero re-prefill, zero new
compiles, prompt+committed resubmit as the always-correct fallback;
engine DEATH replays from the fleet's own forwarded-token record), and
SLO-driven autoscaling (a fleet-level tracker + the §5j dwell/clear
discipline spawning on sustained multiwindow burn and retiring on
sustained clear).  ``FleetSupervisor`` fans per-engine watchdogs in
and escalates unkillable wedges to ``hard_abandon``; the aggregated
``render_prometheus()`` namespaces per-engine series under an
``engine`` label (never double-counting N registries into one scrape)
and adds ``fleet_migrations_total`` /
``fleet_requests_routed_total{reason=affinity|load}``.

Reference parity: the framework-level analog of the reference's
``paddle/fluid/inference/`` serving layer (SURVEY §1), rebuilt
TPU-native over the compiled decode step instead of an executor —
serving-oriented systems work (PAPERS.md, arXiv:2603.09555) treats the
cached decode step as a component inside a request scheduler; this
package is that scheduler.
"""
from . import faults, journal, log, slo, trace, transfer
from .disagg import DisaggregatedServing
from .engine import (PRIORITY_CLASSES, AdmissionTightenedError,
                     DeadlineUnattainableError, QueueFullError,
                     ServingEngine)
from .fleet import ServingFleet
from .journal import (FingerprintMismatchError, JournalCorruptError,
                      JournalWriteError, JournalWriter)
from .http import ServingHTTPFrontend, parse_generate_request
from .log import JsonLinesLogger
from .metrics import (DEFAULT_TIME_BUCKETS, Counter, Gauge, Histogram,
                      MetricsRegistry)
from .slo import Objective, SLOTracker
from .stream import RequestState, ResponseStream, StreamStatus
from .supervisor import EngineHealth, FleetSupervisor, Supervisor
from .trace import FlightRecorder, TraceEvent, Tracer
from .transfer import (TransferFingerprintError, TransferFormatError,
                       TransferReader, TransferVersionError,
                       check_fingerprint, write_transfer)

__all__ = [
    "ServingEngine", "QueueFullError", "DeadlineUnattainableError",
    "AdmissionTightenedError", "PRIORITY_CLASSES",
    "ResponseStream", "StreamStatus", "RequestState",
    "MetricsRegistry", "Counter", "Gauge", "Histogram",
    "DEFAULT_TIME_BUCKETS",
    "ServingHTTPFrontend", "parse_generate_request",
    "faults", "Supervisor", "EngineHealth", "FleetSupervisor",
    "trace", "Tracer", "FlightRecorder", "TraceEvent",
    "slo", "Objective", "SLOTracker",
    "log", "JsonLinesLogger",
    "journal", "JournalWriter", "JournalWriteError",
    "JournalCorruptError", "FingerprintMismatchError",
    "transfer", "write_transfer", "TransferReader", "check_fingerprint",
    "TransferFormatError", "TransferVersionError",
    "TransferFingerprintError",
    "DisaggregatedServing",
    "ServingFleet",
]
