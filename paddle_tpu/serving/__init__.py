"""``paddle_tpu.serving`` — the request scheduler over the decode engine.

The serving stack, bottom to top (docs/DESIGN.md §5a-§5c):

- ``jit.DecodeSession`` — exactly-two-compiles prefill/decode split;
- ``inference.GenerationPool`` — slot-based continuous batching, paged
  KV with a free-list allocator;
- **this package** — the entry point the ROADMAP north star needs:
  request lifecycle + streaming (``ServingEngine.submit`` →
  ``ResponseStream``), per-request deadlines, bounded-queue admission
  control (typed, retryable ``QueueFullError``), mid-generation
  cancellation that frees slots and paged blocks, graceful
  drain/shutdown, hot weight swap, and a serving metrics registry
  (TTFT, inter-token latency, queue depth, occupancy, tokens/s) with
  prometheus text exposition.

Reference parity: the framework-level analog of the reference's
``paddle/fluid/inference/`` serving layer (SURVEY §1), rebuilt
TPU-native over the compiled decode step instead of an executor —
serving-oriented systems work (PAPERS.md, arXiv:2603.09555) treats the
cached decode step as a component inside a request scheduler; this
package is that scheduler.
"""
from .engine import QueueFullError, ServingEngine
from .http import ServingHTTPFrontend, parse_generate_request
from .metrics import (DEFAULT_TIME_BUCKETS, Counter, Gauge, Histogram,
                      MetricsRegistry)
from .stream import RequestState, ResponseStream, StreamStatus

__all__ = [
    "ServingEngine", "QueueFullError",
    "ResponseStream", "StreamStatus", "RequestState",
    "MetricsRegistry", "Counter", "Gauge", "Histogram",
    "DEFAULT_TIME_BUCKETS",
    "ServingHTTPFrontend", "parse_generate_request",
]
