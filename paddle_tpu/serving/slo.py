"""SLO tracking: declarative objectives + multi-window burn-rate alerts.

Metrics (``serving.metrics``) answer *what is happening now*; this
module answers *are we keeping our promises over time*: an
:class:`Objective` declares one promise (TTFT p95 under a threshold,
inter-token p95 under a threshold, availability above a floor), and
:class:`SLOTracker` evaluates it over ROLLING TICK WINDOWS with the
classic multi-window burn-rate pairing:

- **burn rate** = (bad fraction in the window) / (error budget), where
  the error budget is ``1 - target``.  Burn 1.0 means the budget is
  being spent exactly as fast as the objective allows; burn 10 means an
  incident.
- **two windows, one alert**: a FAST window (default 5 ticks — the
  detector) and a SLOW window (default 60 ticks — the de-noiser).  The
  alert is active only while BOTH windows burn at or above
  ``burn_threshold``: the fast window makes the alert flip within ticks
  of an incident, the slow window keeps a single bad tick from paging,
  and — the part that matters for recovery — the fast window DRAINS
  within ticks of the incident ending, clearing the alert while the
  slow window still remembers the damage.  (The Google SRE
  multiwindow/multi-burn-rate policy, with ticks as the time base so
  deterministic pump-mode tests can drive it with no wall clock.)

The tracker is FED FROM THE REAL PATH: the engine's ``_on_token`` hook
reports each TTFT/inter-token observation at the moment it lands, every
terminal ``_finalize`` reports the request's final state, and each tick
rolls the windows.  Uninstalled (``ServingEngine(slo=None)``, the
default) the engine pays ONE ``is None`` test per seam — the fault-
plane pattern, so the hot path stays clean under ``tools/analysis``.

Export: the tracker binds gauges into the engine's
:class:`~.metrics.MetricsRegistry` (``serving_slo_<name>_burn_rate_fast
/ _slow``, ``..._alert_active``, ``..._budget_remaining``) so
``render_prometheus()`` carries SLO state; ``snapshot()`` backs
``GET /slo``; ``health_summary()`` is folded into
``ServingEngine.health()`` so a stall post-mortem ships its SLO state;
alert flips land in the flight recorder (``slo.alert`` /
``slo.alert_cleared``) and the structured log (docs/DESIGN.md §5h).
"""
from __future__ import annotations

import re
from collections import deque
from typing import Dict, List, Optional, Sequence

from ..core.errors import InvalidArgumentError
from . import log as slog
from . import trace

__all__ = ["Objective", "SLOTracker", "DEFAULT_OBJECTIVES"]

_NAME_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")

# the objective vocabulary: what is observed and what "bad" means
_KINDS = ("ttft", "inter_token", "availability")


class Objective:
    """One declarative serving promise.

    ``kind``:
    - ``"ttft"`` / ``"inter_token"``: a latency promise — an
      observation is BAD when it exceeds ``threshold_s``; ``target``
      is the fraction that must be good (``target=0.95`` reads "p95 of
      TTFT stays under ``threshold_s``").
    - ``"availability"``: a terminal-state promise — a request is BAD
      when it finalizes in one of ``bad_states`` (default: FAILED;
      deliberately not CANCELLED/EXPIRED, which are caller/deadline
      decisions, not the engine breaking its promise — pass
      ``bad_states=("FAILED", "EXPIRED")`` to promise deadlines too).

    The error budget is ``1 - target``: the fraction of bad outcomes
    the objective tolerates before its burn rate reaches 1.0.
    """

    __slots__ = ("name", "kind", "target", "threshold_s", "bad_states",
                 "description")

    def __init__(self, name: str, kind: str, target: float,
                 threshold_s: Optional[float] = None,
                 bad_states: Sequence[str] = ("FAILED",),
                 description: str = ""):
        if not _NAME_RE.match(name):
            raise InvalidArgumentError(
                "objective name %r must be a prometheus-safe identifier "
                "([a-zA-Z_][a-zA-Z0-9_]*): it becomes part of the "
                "exported gauge names" % (name,))
        if kind not in _KINDS:
            raise InvalidArgumentError(
                "objective kind must be one of %s, got %r"
                % (", ".join(_KINDS), kind))
        if not 0.0 < float(target) < 1.0:
            # target 1.0 would make the error budget zero and every
            # burn rate infinite; 0 would never alert
            raise InvalidArgumentError(
                "target must be in (0, 1) (e.g. 0.95 = '95%% of events "
                "good'), got %r" % (target,))
        if kind != "availability":
            if threshold_s is None or not float(threshold_s) > 0.0:
                raise InvalidArgumentError(
                    "latency objective %r (kind %r) needs threshold_s "
                    "> 0, got %r" % (name, kind, threshold_s))
            threshold_s = float(threshold_s)
        elif threshold_s is not None:
            raise InvalidArgumentError(
                "availability objective %r takes no threshold_s "
                "(badness is the terminal state, not a latency)"
                % (name,))
        if isinstance(bad_states, str):
            # a bare string IS a Sequence[str]: frozenset('FAILED')
            # would become {'F','A',...}, silently matching nothing —
            # the objective would never alert during a real outage
            raise InvalidArgumentError(
                "bad_states must be a sequence of state names, got the "
                "bare string %r — write bad_states=(%r,)"
                % (bad_states, bad_states))
        bad_states = tuple(bad_states)
        unknown = [s for s in bad_states
                   if s not in ("DONE", "CANCELLED", "EXPIRED",
                                "FAILED")]
        if unknown:
            raise InvalidArgumentError(
                "unknown terminal state(s) %r in bad_states; the "
                "request lifecycle ends in DONE, CANCELLED, EXPIRED "
                "or FAILED" % (unknown,))
        self.name = name
        self.kind = kind
        self.target = float(target)
        self.threshold_s = threshold_s
        self.bad_states = frozenset(bad_states)
        self.description = description

    @property
    def error_budget(self) -> float:
        return 1.0 - self.target


def DEFAULT_OBJECTIVES(ttft_p95_s: float = 1.0,
                       inter_token_p95_s: float = 0.25,
                       availability: float = 0.99) -> List[Objective]:
    """The standard serving objective set the ISSUE/DESIGN docs name:
    TTFT p95, inter-token p95, availability — thresholds are
    deployment-specific, so they are arguments, not constants."""
    return [
        Objective("ttft_p95", "ttft", 0.95, threshold_s=ttft_p95_s,
                  description="95%% of first tokens within %gs"
                  % ttft_p95_s),
        Objective("inter_token_p95", "inter_token", 0.95,
                  threshold_s=inter_token_p95_s,
                  description="95%% of token gaps within %gs"
                  % inter_token_p95_s),
        Objective("availability", "availability", availability,
                  description="fraction of requests that do not FAIL"),
    ]


class _ObjectiveState:
    """Rolling-window accounting for one objective.

    Single-writer (the ticking thread, under the engine lock); read
    lock-free by ``health()``/``snapshot()`` — every exported field is
    a plain attribute, so a torn read costs staleness, never a hang
    (the ``EngineHealth`` discipline)."""

    __slots__ = ("objective", "cur_good", "cur_bad", "window",
                 "slow_good", "slow_bad", "fast_good", "fast_bad",
                 "fast_burn", "slow_burn",
                 "alert_active", "alerts_fired", "total_good",
                 "total_bad")

    def __init__(self, objective: Objective, slow_window: int):
        self.objective = objective
        self.cur_good = 0
        self.cur_bad = 0
        # per-tick (good, bad) pairs, newest right; maxlen evicts the
        # tick that just left the slow window
        self.window: deque = deque(maxlen=slow_window)
        self.slow_good = 0
        self.slow_bad = 0
        self.fast_good = 0
        self.fast_bad = 0
        self.fast_burn = 0.0
        self.slow_burn = 0.0
        self.alert_active = False
        self.alerts_fired = 0
        self.total_good = 0
        self.total_bad = 0

    def observe(self, bad: bool) -> None:
        if bad:
            self.cur_bad += 1
            self.total_bad += 1
        else:
            self.cur_good += 1
            self.total_good += 1

    def roll(self, fast_window: int, burn_threshold: float) -> Optional[bool]:
        """Close the current tick's bucket and re-evaluate both
        windows; returns the new alert state when it FLIPPED, else
        None.

        Both windows carry RUNNING sums — the tick path (idle ticks
        included) does O(1) arithmetic and one deque append, never a
        window copy; deque end-indexing fetches the pair leaving the
        trailing fast window without touching the rest."""
        evicted = None
        if len(self.window) == self.window.maxlen:
            evicted = self.window[0]  # about to be evicted by append
            self.slow_good -= evicted[0]
            self.slow_bad -= evicted[1]
        self.window.append((self.cur_good, self.cur_bad))
        self.slow_good += self.cur_good
        self.slow_bad += self.cur_bad
        self.fast_good += self.cur_good
        self.fast_bad += self.cur_bad
        if len(self.window) > fast_window:
            # the (fast_window+1)-th pair from the right just left the
            # trailing fast window and is still in the deque
            g, b = self.window[-fast_window - 1]
            self.fast_good -= g
            self.fast_bad -= b
        elif evicted is not None and len(self.window) == fast_window:
            # slow_window == fast_window: the leaving pair IS the one
            # the maxlen append evicted
            self.fast_good -= evicted[0]
            self.fast_bad -= evicted[1]
        self.cur_good = 0
        self.cur_bad = 0
        fg, fb = self.fast_good, self.fast_bad
        budget = self.objective.error_budget
        self.fast_burn = (fb / (fg + fb) / budget) if (fg + fb) else 0.0
        self.slow_burn = (self.slow_bad
                          / (self.slow_good + self.slow_bad)
                          / budget) \
            if (self.slow_good + self.slow_bad) else 0.0
        active = (self.fast_burn >= burn_threshold
                  and self.slow_burn >= burn_threshold)
        if active == self.alert_active:
            return None
        self.alert_active = active
        if active:
            self.alerts_fired += 1
        return active


class SLOTracker:
    """Evaluate a set of :class:`Objective` promises over rolling tick
    windows; the engine owns one (``ServingEngine(slo=tracker)``) and
    feeds it from the real metrics path.

    Windows are counted in TICKS (the engine's scheduling quantum), so
    deterministic pump-mode tests drive alerting with zero wall-clock
    dependence — exactly how the deadline machinery is tested.
    """

    def __init__(self, objectives: Sequence[Objective],
                 fast_window: int = 5, slow_window: int = 60,
                 burn_threshold: float = 1.0):
        objectives = list(objectives)
        if not objectives:
            raise InvalidArgumentError(
                "SLOTracker needs at least one Objective")
        names = [o.name for o in objectives]
        if len(set(names)) != len(names):
            raise InvalidArgumentError(
                "objective names must be unique, got %r" % (names,))
        if int(fast_window) < 1 or int(slow_window) < int(fast_window):
            raise InvalidArgumentError(
                "need 1 <= fast_window <= slow_window, got fast=%r "
                "slow=%r" % (fast_window, slow_window))
        if not float(burn_threshold) > 0.0:
            raise InvalidArgumentError(
                "burn_threshold must be > 0, got %r" % (burn_threshold,))
        self.fast_window = int(fast_window)
        self.slow_window = int(slow_window)
        self.burn_threshold = float(burn_threshold)
        self._states: Dict[str, _ObjectiveState] = {
            o.name: _ObjectiveState(o, self.slow_window)
            for o in objectives}
        self.ticks = 0
        self._gauges: Optional[dict] = None

    # -- fed from the engine's real path ---------------------------------
    def observe_latency(self, kind: str, seconds: float) -> None:
        """One TTFT or inter-token observation (engine ``_on_token``)."""
        for st in self._states.values():
            o = st.objective
            if o.kind == kind:
                st.observe(seconds > o.threshold_s)

    def observe_terminal(self, state: str) -> None:
        """One request reached a terminal state (engine ``_finalize``)."""
        for st in self._states.values():
            o = st.objective
            if o.kind == "availability":
                st.observe(state in o.bad_states)

    def note_tick(self) -> None:
        """Roll every objective's windows at the tick boundary; alert
        flips land in the flight recorder and the structured log the
        moment they happen."""
        self.ticks += 1
        for st in self._states.values():
            flipped = st.roll(self.fast_window, self.burn_threshold)
            if flipped is None:
                continue
            event = "slo.alert" if flipped else "slo.alert_cleared"
            trace.instant(event, objective=st.objective.name,
                          fast_burn=round(st.fast_burn, 4),
                          slow_burn=round(st.slow_burn, 4))
            slog.emit(event, objective=st.objective.name,
                      fast_burn=round(st.fast_burn, 4),
                      slow_burn=round(st.slow_burn, 4),
                      burn_threshold=self.burn_threshold)
        if self._gauges is not None:
            for name, st in self._states.items():
                g = self._gauges[name]
                g["fast"].set(st.fast_burn)
                g["slow"].set(st.slow_burn)
                g["active"].set(1.0 if st.alert_active else 0.0)
                g["budget"].set(max(0.0, 1.0 - st.slow_burn))

    # -- export surfaces --------------------------------------------------
    def bind_metrics(self, registry) -> None:
        """Register per-objective gauges on ``registry`` so the SLO
        state rides every ``render_prometheus()`` scrape.  Idempotent
        per registry (create-or-get semantics)."""
        gauges = {}
        for name, st in self._states.items():
            o = st.objective
            gauges[name] = {
                "fast": registry.gauge(
                    "serving_slo_%s_burn_rate_fast" % name,
                    "error-budget burn rate over the fast %d-tick "
                    "window (%s)" % (self.fast_window, o.kind)),
                "slow": registry.gauge(
                    "serving_slo_%s_burn_rate_slow" % name,
                    "error-budget burn rate over the slow %d-tick "
                    "window" % self.slow_window),
                "active": registry.gauge(
                    "serving_slo_%s_alert_active" % name,
                    "1 while both windows burn >= the threshold"),
                "budget": registry.gauge(
                    "serving_slo_%s_budget_remaining" % name,
                    "1 - slow-window burn rate, floored at 0"),
            }
        self._gauges = gauges

    @property
    def alerts_active(self) -> int:
        return sum(1 for st in self._states.values() if st.alert_active)

    def alerting_names(self) -> List[str]:
        """Names of objectives whose multi-window alert is ACTIVE right
        now (both burn windows at/over the threshold) — the control
        signal the serving engine's degradation ladder steps on.  Plain
        attribute reads, safe from the tick path (one tuple walk per
        tick when a ladder is configured)."""
        return [name for name, st in self._states.items()
                if st.alert_active]

    def health_summary(self) -> dict:
        """The compact record ``ServingEngine.health()`` folds in —
        plain-attribute reads only, safe lock-free during a wedge."""
        return {
            "alerts_active": self.alerts_active,
            "alerting": sorted(name for name, st in self._states.items()
                               if st.alert_active),
            "ticks": self.ticks,
        }

    def snapshot(self) -> dict:
        """The full JSON-safe state — the ``GET /slo`` body."""
        objectives = []
        for name, st in self._states.items():
            o = st.objective
            objectives.append({
                "name": name,
                "kind": o.kind,
                "target": o.target,
                "threshold_s": o.threshold_s,
                "error_budget": o.error_budget,
                "bad_states": (sorted(o.bad_states)
                               if o.kind == "availability" else None),
                "description": o.description,
                "fast_burn_rate": st.fast_burn,
                "slow_burn_rate": st.slow_burn,
                "alert_active": st.alert_active,
                "alerts_fired": st.alerts_fired,
                "window_good": st.slow_good,
                "window_bad": st.slow_bad,
                "total_good": st.total_good,
                "total_bad": st.total_bad,
            })
        return {
            "fast_window_ticks": self.fast_window,
            "slow_window_ticks": self.slow_window,
            "burn_threshold": self.burn_threshold,
            "ticks": self.ticks,
            "alerts_active": self.alerts_active,
            "objectives": objectives,
        }
