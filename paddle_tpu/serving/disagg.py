"""Disaggregated prefill/decode serving over the K/V hand-off contract.

:class:`DisaggregatedServing` is the front that owns one prefill-role
engine and one decode-role engine (docs/DESIGN.md §5n).  Prefill is
compute-bound and decode is bandwidth-bound — the PR 14 AOT cost stamps
prove it per-executable — so the tiers size independently: a small
prefill tier absorbs long prompts without ever blocking a resident
decode, and the decode tier never compiles a prefill-chunk executable.

The request path: ``submit()`` routes to the prefill tier (admission
control, deadline shed — the front's estimate includes the observed
hand-off wait), whose chunked prefill emits the request's FIRST token
and parks it; the tick-edge export sweep writes the K/V transfer file
(``xfer.write`` seam) and fires ``on_handoff``; the front's bridge
adopts it into the decode tier (``adopt_transfer`` →
``adopt_spill`` → the PR 15 upload path — no re-prefill), and tokens
keep flowing on the SAME front stream the caller holds.  Byte-identity
is the contract: the hand-off carries bit-exact K/V for exactly the
committed positions, and any adoption miss falls back to
prompt+committed resubmit — greedy decode is identical either way, so
a hand-off can never change tokens, only where they are computed.

The front is deliberately pump-mode only: one thread drives
``pump()`` → prefill tick → bridge → decode tick → bridge, which keeps
every test deterministic and matches how the bench leg measures it.
Front-observed ``serving_ttft_seconds`` / ``serving_inter_token_seconds``
include the hand-off wait — end-to-end honest, what the ``serving_disagg``
bench leg reads — while each tier's own registry keeps its local view.
"""
from __future__ import annotations

import os
import queue
import time
from typing import Dict, List, Optional

import numpy as np

from ..core.errors import PreconditionNotMetError
from . import log as slog
from . import trace
from .engine import DeadlineUnattainableError, ServingEngine
from .metrics import MetricsRegistry
from .stream import (RequestState, ResponseStream, StreamStatus,
                     _TERMINAL)

__all__ = ["DisaggregatedServing"]


class _FrontRecord:
    """One request's front-side bookkeeping across both tiers."""

    __slots__ = ("rid", "stream", "prefill_stream", "decode_stream",
                 "tokens", "submit_t", "first_t", "last_t",
                 "prompt_len", "max_new", "priority", "tenant",
                 "deadline_abs")

    def __init__(self, rid, stream, prefill_stream, prompt_len,
                 max_new, submit_t, priority, tenant, deadline_abs):
        self.rid = rid
        self.stream = stream
        self.prefill_stream = prefill_stream
        self.decode_stream = None
        self.tokens: List[int] = []
        self.submit_t = submit_t
        self.first_t = None
        self.last_t = None
        self.prompt_len = prompt_len
        self.max_new = max_new
        self.priority = priority
        self.tenant = tenant
        self.deadline_abs = deadline_abs


class DisaggregatedServing:
    """One prefill tier + one decode tier behind a fused-looking front.

    ``transfer_dir`` is the directory both tiers share — the hand-off
    files live there under the same naming the PR 15 spill tier uses,
    so migration, crash restore and disaggregation stay ONE mechanism.
    ``prefill_chunk_tokens`` sizes the prefill tier's chunk executable;
    ``prefill_slots``/``decode_slots`` size the tiers independently
    (capacity keys are excluded from the transfer fingerprint check
    for exactly this reason).  Shared ``**pool_kwargs`` (sampling
    config, ``block_size``, ``cache_dtype``, ...) go to BOTH pools —
    they must, or the fingerprint check would refuse every hand-off;
    ``prefill_overrides``/``decode_overrides`` patch capacity-class
    knobs per tier (``num_blocks``, ``max_queue`` is front-level)."""

    def __init__(self, model, max_len: int, *,
                 transfer_dir: str, prefill_chunk_tokens: int,
                 prefill_slots: int = 2, decode_slots: int = 4,
                 max_queue: int = 64, clock=None,
                 metrics: Optional[MetricsRegistry] = None,
                 prefill_overrides: Optional[dict] = None,
                 decode_overrides: Optional[dict] = None,
                 **pool_kwargs):
        self._clock = clock if clock is not None else time.monotonic
        pool_kwargs.setdefault("cache_layout", "paged")
        pk = dict(pool_kwargs)
        pk.update(prefill_overrides or {})
        dk = dict(pool_kwargs)
        dk.update(decode_overrides or {})
        # each tier keeps its OWN metrics registry (tier-local TTFT on
        # the prefill tier would otherwise average into the decode
        # tier's ITL); the front's registry carries the end-to-end and
        # hand-off surfaces below
        self.prefill = ServingEngine(
            model, max_len, slots=prefill_slots, max_queue=max_queue,
            clock=clock, role="prefill", spill_tier="disk",
            spill_dir=transfer_dir,
            prefill_chunk_tokens=prefill_chunk_tokens, **pk)
        self.decode = ServingEngine(
            model, max_len, slots=decode_slots, max_queue=max_queue,
            clock=clock, role="decode", spill_tier="disk",
            spill_dir=transfer_dir, **dk)
        self.prefill.on_handoff = self._on_handoff
        self._records: Dict[object, _FrontRecord] = {}
        # rid -> hand-off info dicts exported but not yet adopted
        # (filled by the prefill tick's sweep, drained by _bridge)
        self._handoffs: Dict[object, dict] = {}
        self._draining = False

        self.metrics = metrics if metrics is not None \
            else MetricsRegistry()
        m = self.metrics
        self._c_submitted = m.counter(
            "serving_requests_submitted_total",
            "requests admitted at the disaggregated front")
        self._c_transfers = m.counter(
            "serving_kv_transfers_total",
            "prefill→decode K/V hand-offs bridged by the front")
        self._c_transfer_bytes = m.counter(
            "serving_kv_transfer_bytes_total",
            "K/V bytes handed off through transfer files (int8 caches "
            "count int8 K/V + fp32 scales — the quantized wire format)")
        self._c_degraded = m.counter(
            "serving_handoffs_degraded_total",
            "hand-offs that fell back to prompt+committed resubmit "
            "(export failed or the transfer file could not be adopted)")
        self._h_handoff = m.histogram(
            "serving_handoff_wait_s",
            "export-to-adopt wait of one K/V hand-off")
        self._h_ttft = m.histogram(
            "serving_ttft_seconds",
            "front-observed submit-to-first-token latency "
            "(end-to-end: includes the hand-off wait)")
        self._h_itl = m.histogram(
            "serving_inter_token_seconds",
            "front-observed gap between consecutive tokens "
            "(end-to-end: the hand-off gap rides the first decode-tier "
            "token)")

    # -- admission -------------------------------------------------------
    def submit(self, input_ids, max_new_tokens: int, request_id=None,
               deadline_s: Optional[float] = None, priority=0,
               tenant=None) -> ResponseStream:
        """Admit one request; returns the FRONT's stream — tokens flow
        across the hand-off on this one handle.  Deadline shedding
        happens HERE with the cross-tier estimate (prefill ticks +
        observed mean hand-off wait + decode ticks): the tiers' own
        estimators cannot see each other's backlog, and an admission
        the hand-off wait alone would blow must shed at the door, not
        expire mid-transfer.  Scheduling metadata (deadline, priority,
        tenant) is carried across the hand-off — test-pinned."""
        if self._draining:
            raise PreconditionNotMetError(
                "disaggregated front is draining/shut down")
        ids = np.asarray(getattr(input_ids, "value", input_ids))
        prompt_len = int(ids.shape[0]) if ids.ndim else 0
        if deadline_s is not None:
            est = self._deadline_estimate_s(int(max_new_tokens),
                                            prompt_len)
            if est is not None and est > float(deadline_s):
                raise DeadlineUnattainableError(
                    "deadline_s=%.3g cannot be met across the "
                    "disaggregated pair: prefill + hand-off + decode "
                    "put completion ~%.3gs out; shed at admission "
                    "(retryable)" % (float(deadline_s), est),
                    retry_after_s=max(0.001, est - float(deadline_s)))
        ps = self.prefill.submit(ids, max_new_tokens,
                                 request_id=request_id,
                                 deadline_s=deadline_s,
                                 priority=priority, tenant=tenant)
        rid = ps.request_id
        now = self._clock()
        stream = ResponseStream(self, rid, int(max_new_tokens))
        self._records[rid] = _FrontRecord(
            rid, stream, ps, prompt_len, int(max_new_tokens), now,
            priority, tenant,
            None if deadline_s is None else now + float(deadline_s))
        self._c_submitted.inc()
        return stream

    # -- the hand-off bridge ---------------------------------------------
    def _on_handoff(self, rid, info) -> None:
        # fires inside the prefill tier's export sweep, BEFORE the
        # tier finalizes HANDED_OFF — so by the time the front's
        # bridge sees that terminal, the hand-off record exists
        self._handoffs[rid] = info
        self._c_transfers.inc()
        self._c_transfer_bytes.inc(info.get("transfer_bytes") or 0)
        if info.get("error") or not info.get("path"):
            self._c_degraded.inc()

    def _adopt(self, rec: _FrontRecord, info: dict) -> None:
        wait_s = max(0.0, self._clock() - info["exported_at"])
        self._h_handoff.observe(wait_s)
        res = self.decode.adopt_transfer(
            rec.rid, info["prompt"], info["tokens"],
            info["max_new_tokens"], priority=info["priority"],
            tenant=info["tenant"], deadline_abs=info["deadline_abs"])
        rec.decode_stream = res["stream"]
        if not res["adopted_from_file"] and info.get("path") \
                and not info.get("error"):
            # the file existed but the decode tier could not adopt it
            # (stale/alien/structural) — degraded, still byte-identical
            self._c_degraded.inc()
        trace.instant("xfer.handoff", rid=rec.rid,
                      wait_s=round(wait_s, 6),
                      transfer_bytes=info.get("transfer_bytes"),
                      adopted_from_file=res["adopted_from_file"])
        slog.emit("xfer.handoff", rid=rec.rid,
                  wait_s=round(wait_s, 6),
                  transfer_bytes=info.get("transfer_bytes"),
                  adopted_from_file=res["adopted_from_file"],
                  committed_tokens=len(info["tokens"]))

    def _forward(self, rec: _FrontRecord, src: ResponseStream) -> bool:
        """Drain one tier stream's queue into the front stream; True
        when the tier delivered its terminal."""
        while True:
            try:
                item = src._q.get_nowait()
            except queue.Empty:
                return False
            if item is _TERMINAL:
                return True
            now = self._clock()
            if rec.first_t is None:
                rec.first_t = now
                self._h_ttft.observe(now - rec.submit_t)
            else:
                self._h_itl.observe(now - rec.last_t)
            rec.last_t = now
            rec.tokens.append(int(item))
            rec.stream._put_token(int(item))

    def _finalize_front(self, rec: _FrontRecord, state: str,
                        reason, error=None) -> None:
        now = self._clock()
        toks = np.asarray(rec.tokens, np.int32)
        trace.instant("req." + state.lower(), rid=rec.rid,
                      reason=reason, new_tokens=int(toks.size),
                      front=True, error=error)
        rec.stream._finalize(StreamStatus(
            request_id=rec.rid, state=state, finish_reason=reason,
            tokens=toks, prompt_tokens=rec.prompt_len,
            new_tokens=int(toks.size),
            ttft_s=(None if rec.first_t is None
                    else rec.first_t - rec.submit_t),
            total_s=now - rec.submit_t, error=error))
        self._records.pop(rec.rid, None)

    def _bridge(self) -> None:
        for rec in list(self._records.values()):
            info = self._handoffs.pop(rec.rid, None)
            if info is not None and rec.decode_stream is None:
                self._adopt(rec, info)
            done = self._forward(rec, rec.prefill_stream)
            if done:
                st = rec.prefill_stream.status
                if st.state != RequestState.HANDED_OFF:
                    # the request terminated ON the prefill tier:
                    # finished at its first token, expired, or failed
                    # before hand-off — that terminal is the front's
                    self._finalize_front(rec, st.state,
                                         st.finish_reason,
                                         error=st.error)
                    continue
            if rec.decode_stream is not None \
                    and self._forward(rec, rec.decode_stream):
                st = rec.decode_stream.status
                self._finalize_front(rec, st.state, st.finish_reason,
                                     error=st.error)

    # -- drive (pump mode only, like every tier-1 test) ------------------
    def is_running(self) -> bool:
        """The front is pump-mode only (no background thread): the
        caller — or the stream iterating — is the engine's legs."""
        return False

    def pump(self, steps: int = 1) -> bool:
        """One front tick per step: prefill tier tick → bridge (adopt
        fresh hand-offs so the decode tick can resume them
        immediately) → decode tier tick → bridge (forward its tokens).
        True while front-live requests remain."""
        for _ in range(int(steps)):
            self.prefill.pump(1)
            self._bridge()
            self.decode.pump(1)
            self._bridge()
            if not self._records:
                break
        return bool(self._records)

    # -- lifecycle -------------------------------------------------------
    def cancel(self, request_id) -> bool:
        """Cancel wherever the request lives: on the prefill tier, in
        transit (the exported-but-not-adopted window — the transfer
        file is deleted, BOTH tiers are already clean), or on the
        decode tier.  The front stream ends CANCELLED; idempotent."""
        rec = self._records.get(request_id)
        if rec is None:
            return False
        info = self._handoffs.pop(request_id, None)
        if rec.decode_stream is not None:
            self.decode.cancel(request_id)
        elif info is not None:
            # mid-hand-off: the prefill tier already exported (its
            # slot and blocks are free) and the decode tier never saw
            # the request — only the file needs reclaiming
            if info.get("path"):
                try:
                    os.remove(info["path"])
                except OSError:
                    pass
        else:
            self.prefill.cancel(request_id)
        self._finalize_front(rec, RequestState.CANCELLED, "cancelled")
        return True

    def request_state(self, request_id) -> Optional[str]:
        """Front-perspective lifecycle state (the stream handle's
        ``.state``): the decode tier's once adopted, PREEMPTED while
        the hand-off is in transit (parked, about to resume), else the
        prefill tier's."""
        rec = self._records.get(request_id)
        if rec is None:
            return None
        if rec.decode_stream is not None:
            return self.decode.request_state(request_id) \
                or RequestState.DECODING
        if request_id in self._handoffs:
            return RequestState.PREEMPTED
        return self.prefill.request_state(request_id)

    def _deadline_estimate_s(self, max_new_tokens: int,
                             prompt_len: int = 0) -> Optional[float]:
        """Cross-tier completion estimate: the prefill tier's chunk
        ticks for this prompt (+1 first token), PLUS the observed mean
        hand-off wait (``serving_handoff_wait_s`` — without it the
        front would admit requests whose deadline the transfer alone
        blows, the same class of under-estimate the PR 12 per-request
        chunk-ticks fix closed), PLUS the decode tier's ticks for the
        remaining budget.  None until BOTH tiers have measured a tick
        (never shed on a guess)."""
        pe = self.prefill._deadline_estimate_s(1, prompt_len)
        de = self.decode._deadline_estimate_s(
            max(0, int(max_new_tokens) - 1))
        if pe is None or de is None:
            return None
        h = self._h_handoff
        wait = (h.sum / h.count) if h.count else 0.0
        return pe + wait + de

    def drain(self, timeout_s: Optional[float] = None) -> bool:
        """Stop admissions, pump until every front-live request
        terminates; False on timeout (wall clock, like the engines)."""
        self._draining = True
        deadline = None if timeout_s is None \
            else time.monotonic() + timeout_s
        while self._records:
            self.pump(1)
            if deadline is not None and time.monotonic() >= deadline:
                return False
        return True

    def shutdown(self, drain: bool = True) -> None:
        """Graceful stop: drain (or cancel) front-live requests, then
        shut both tiers down (journals flushed and closed)."""
        if drain:
            self.drain()
        else:
            self._draining = True
            for rid in list(self._records):
                self.cancel(rid)
        self.prefill.shutdown(drain=False)
        self.decode.shutdown(drain=False)

    # -- observability ---------------------------------------------------
    def health(self) -> dict:
        """Merged probe body: healthy iff BOTH tiers are, with each
        tier's full snapshot nested and the hand-off surface on top."""
        ph = self.prefill.health()
        dh = self.decode.health()
        return {"healthy": ph["healthy"] and dh["healthy"],
                "state": ("draining" if self._draining
                          else "serving" if self._records else "idle"),
                "live_requests": len(self._records),
                "handoffs_in_flight": len(self._handoffs),
                "prefill": ph, "decode": dh}

    def compile_counts(self) -> dict:
        """Per-role compile accounting — the tier pins: the decode
        tier's dict never grows a ``prefill_chunk`` key, the prefill
        tier's ``pool_decode`` stays 0 (test-pinned)."""
        return {"prefill": self.prefill.compile_counts(),
                "decode": self.decode.compile_counts()}

    @property
    def live_requests(self) -> int:
        return len(self._records)

    @property
    def draining(self) -> bool:
        return self._draining
