"""Per-request token streaming over the serving engine.

``ServingEngine.submit()`` returns a :class:`ResponseStream`: an
iterable that yields token ids the moment the pool's decode step emits
them, then ends; the terminal :class:`StreamStatus` record (finish
reason, token counts, timings) is available as ``stream.status`` /
``stream.result()`` afterwards.

The backing queue is BOUNDED at the request's own declared budget
(``max_new_tokens`` + the terminal marker): no request can buffer more
output than it was admitted for, so a slow consumer costs memory
proportional to what admission control already approved — never an
unbounded pile-up — and the engine's producer side can always
``put_nowait`` without risking a deadlock against its own step loop.

Iteration adapts to the engine's drive mode: under the background
step-loop thread it blocks on the queue (tokens arrive from the owning
thread); in synchronous ``pump()`` mode it drives ``engine.pump(1)``
itself between reads, so ``for tok in engine.submit(...)`` works
single-threaded and deterministically — the form every tier-1 test
uses.
"""
from __future__ import annotations

import collections
import queue
import threading
import time
from typing import Optional

from . import faults

__all__ = ["RequestState", "ResponseStream", "StreamStatus"]


class RequestState:
    """Request lifecycle: QUEUED → PREFILLING → DECODING → terminal.

    ``PREEMPTED`` is a NON-terminal detour off DECODING: the scheduler
    evicted the request mid-decode (its K/V spilled to the host tier)
    and will resume it — the stream stays open, tokens already
    delivered stand, and the request returns to DECODING at resume.

    ``HANDED_OFF`` is terminal FOR THE TIER, not for the request: a
    prefill-role engine exported the request's K/V over the transfer
    contract and a decode-role engine now owns it (docs §5n).  The
    disaggregated front never surfaces it — its bridged stream keeps
    flowing across the hand-off — but tier-local observers (the
    journal, per-tier metrics) see the prefill tier's involvement end
    here."""

    QUEUED = "QUEUED"
    PREFILLING = "PREFILLING"
    DECODING = "DECODING"
    PREEMPTED = "PREEMPTED"
    DONE = "DONE"
    CANCELLED = "CANCELLED"
    EXPIRED = "EXPIRED"
    FAILED = "FAILED"
    HANDED_OFF = "HANDED_OFF"
    TERMINAL = frozenset({DONE, CANCELLED, EXPIRED, FAILED, HANDED_OFF})


# the terminal record delivered once per request: finish_reason is the
# decode layer's eos/length for DONE, else the scheduler's
# cancelled/deadline/error; ttft_s is None when the request never
# produced a token (expired in the queue, cancelled pre-admission)
StreamStatus = collections.namedtuple(
    "StreamStatus",
    ["request_id", "state", "finish_reason", "tokens", "prompt_tokens",
     "new_tokens", "ttft_s", "total_s", "error"])

_TERMINAL = object()


class ResponseStream:
    """Iterable of one request's generated token ids + terminal status.

    Engine-side producers call ``_put_token``/``_finalize``; consumers
    iterate (or call :meth:`result`).  Thread-safe: the queue and the
    done-event are the only shared state."""

    def __init__(self, engine, request_id, max_new_tokens: int):
        self._engine = engine
        self.request_id = request_id
        # tokens <= max_new_tokens plus exactly one terminal marker, so
        # the producer can never block or overflow even if the consumer
        # never reads a single token
        self._q: queue.Queue = queue.Queue(maxsize=int(max_new_tokens) + 1)
        self._done = threading.Event()
        self._status: Optional[StreamStatus] = None

    # -- engine side -----------------------------------------------------
    def _put_token(self, tok: int) -> None:
        # `stream.deliver` is the injection seam for delivery failures;
        # the engine delivers BEFORE committing a token, so a fault here
        # means recovery regenerates exactly this token (no loss, no
        # duplicate — see ServingEngine._on_token)
        faults.fire("stream.deliver")
        self._q.put_nowait(tok)

    def _finalize(self, status: StreamStatus) -> None:
        self._status = status
        self._q.put_nowait(_TERMINAL)
        self._done.set()

    # -- consumer side ---------------------------------------------------
    @property
    def status(self) -> Optional[StreamStatus]:
        """The terminal record, or None while the request is live."""
        return self._status

    @property
    def state(self) -> str:
        s = self._status
        if s is not None:
            return s.state
        return self._engine.request_state(self.request_id)

    def done(self) -> bool:
        return self._done.is_set()

    def __iter__(self):
        while True:
            try:
                item = self._q.get_nowait()
            except queue.Empty:
                if self._done.is_set():
                    return
                if self._engine.is_running():
                    item = self._q.get()  # the step-loop thread feeds us
                else:
                    # synchronous mode: WE are the engine's legs
                    if not self._engine.pump(1) and not self._done.is_set():
                        return  # engine drained under us (shutdown race)
                    continue
            if item is _TERMINAL:
                return
            yield item

    def result(self, timeout_s: Optional[float] = None
               ) -> Optional[StreamStatus]:
        """Wait for the terminal record (pumping the engine inline when
        it has no background thread); None on timeout — honored in both
        drive modes, so a bounded caller never rides out a long
        generation it did not ask to wait for."""
        deadline = None if timeout_s is None \
            else time.monotonic() + timeout_s
        if not self._done.is_set() and not self._engine.is_running():
            while not self._done.is_set() and \
                    (deadline is None or time.monotonic() < deadline):
                if not self._engine.pump(1):
                    break
        self._done.wait(
            None if deadline is None
            else max(0.0, deadline - time.monotonic()))
        return self._status
