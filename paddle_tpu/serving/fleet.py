"""Multi-engine serving fleet: prefix-affinity routing, live request
migration, SLO-driven autoscaling (docs/DESIGN.md §5o).

:class:`ServingFleet` fronts N fused :class:`~.engine.ServingEngine`
replicas with the single-engine API (``submit``/stream/``cancel``/
``metrics``) — the router tier the single-node stack (PRs 11–16) was
missing.  Three pillars, all pure-Python traffic plumbing over signals
the engine already exports as data:

- **Prefix-affinity routing.**  Every engine exposes its resident
  prefix index as a chain-hash digest
  (``GenerationPool.prefix_digest`` — the same chained
  ``hash((parent_key, block_tokens))`` keys ``_match_prefix`` walks,
  epoch-cached so an unchanged index costs one int compare).  The
  router replays that chain over a new prompt's head blocks against
  each engine's cached key set: the engine matching the most
  consecutive blocks already HOLDS that prefix's K/V, so routing there
  turns the fleet's N separate prefix caches into an approximately
  partitioned one.  No match falls back to least-loaded placement
  scored from ``health()`` state, queue depth + live requests per
  slot, degradation level, and per-engine SLO burn — the engine's own
  backpressure signals.  The digest is a HINT, not a promise (blocks
  may be evicted between digest and admission; router-side matching
  skips the token-equality collision check): a wrong guess costs only
  placement, never correctness.

- **Live request migration.**  ``retire_engine`` drains a victim
  through the PR 15/16 machinery: the donor engine preempts each
  DECODING request into its disk-tier transfer file, DETACHES the file
  (``GenerationPool.detach_spilled`` — the pool forgets the request,
  the ``.npz`` survives), finalizes its side ``HANDED_OFF``, and the
  adopting peer re-parks it via ``adopt_migration`` → ``adopt_spill``
  with zero re-prefill and zero new compiles.  Any miss (queued,
  mid-prefill, host-tier, stale file) degrades to prompt+committed
  resubmit — byte-identical under greedy decoding, the same O(1)-cache
  contract every recovery path in this stack leans on.  Engine DEATH
  is the same flow minus the donor's cooperation: the fleet's own
  per-request token record (what it forwarded to the caller) is the
  crash-honest resume point, and survivors regenerate the rest.
  Either way the caller's stream never closes: scale-down and engine
  death never drop a token.

- **SLO-driven autoscaling.**  A fleet-level
  :class:`~.slo.SLOTracker` observes front-side TTFT / inter-token
  latency and terminals; the controller reuses the PR 12 degradation
  ladder's dwell/clear discipline at fleet scope — spawn an engine
  after a sustained multiwindow burn alert (``scale_dwell_ticks``
  since the last change), retire the least-loaded engine after
  ``scale_clear_ticks`` consecutive alert-free ticks with fleet
  utilization under ``scale_down_util``.  Dwell prevents flapping on
  a burst edge; multiwindow burn (fast AND slow) prevents reacting to
  a single slow token.

The fleet is pump-mode only, like :class:`~.disagg.DisaggregatedServing`:
one thread drives ``pump()`` → per-engine ticks → forward → autoscale,
so every test is deterministic.  Engines must be CONSTRUCTED by the
``engine_factory(engine_id, metrics_registry)`` callback — fused role,
not started — and should share one ``spill_dir`` (and one cache/
sampling config) or migration quietly loses its file fast path (the
fingerprint check refuses alien files; resubmit still covers
correctness).  Aggregated ``render_prometheus()`` namespaces every
per-engine series with an ``engine`` label so N registries never
double-count into one scrape, and adds the fleet-level counters
(``fleet_migrations_total``,
``fleet_requests_routed_total{reason=affinity|load}``, ...).
"""
from __future__ import annotations

import time
from typing import Dict, List, Optional

import numpy as np

from ..core.errors import (InvalidArgumentError, NotFoundError,
                           PreconditionNotMetError, UnavailableError)
from ..inference.generation import DuplicateRequestError
from . import log as slog
from . import trace
from .engine import QueueFullError, ServingEngine
from .metrics import (Counter, Histogram, MetricsRegistry, _fmt,
                      escape_help, escape_label_value)
from .slo import DEFAULT_OBJECTIVES, SLOTracker
from .stream import (RequestState, ResponseStream, StreamStatus,
                     _TERMINAL)

__all__ = ["ServingFleet"]


class _EngineHandle:
    """One engine's fleet-side bookkeeping: identity, lifecycle state
    (``active`` → ``draining`` → ``retired``, or ``dead``), its own
    metrics registry (rendered under an ``engine`` label), and the
    epoch-cached prefix digest the router matches against."""

    __slots__ = ("engine_id", "engine", "registry", "state", "digest",
                 "born_tick")

    def __init__(self, engine_id: str, engine, registry, born_tick: int):
        self.engine_id = engine_id
        self.engine = engine
        self.registry = registry
        self.state = "active"
        self.digest: Optional[dict] = None
        self.born_tick = born_tick


class _FleetRecord:
    """One request's front-side bookkeeping across migrations.
    ``tokens`` is every token forwarded to the caller — the
    crash-honest ground truth a dead engine's requests resume from."""

    __slots__ = ("rid", "stream", "engine_id", "engine_stream",
                 "prompt", "prompt_len", "tokens", "max_new",
                 "deadline_abs", "submit_t", "first_t", "last_t",
                 "priority", "tenant", "migrations", "sampling",
                 "adapter")

    def __init__(self, rid, stream, engine_id, engine_stream, prompt,
                 max_new, submit_t, priority, tenant, deadline_abs,
                 sampling=None, adapter=0):
        self.rid = rid
        self.stream = stream
        self.engine_id = engine_id
        self.engine_stream = engine_stream
        self.prompt = prompt
        self.prompt_len = int(prompt.shape[0]) if prompt.ndim else 0
        self.tokens: List[int] = []
        self.max_new = max_new
        self.deadline_abs = deadline_abs
        self.submit_t = submit_t
        self.first_t = None
        self.last_t = None
        self.priority = priority
        self.tenant = tenant
        self.migrations = 0
        # the engine-resolved per-request sampling config and adapter
        # id (docs §5q): the death-path re-adoption hands them to the
        # adopter so a migrated request continues ITS stream under ITS
        # adapter — the fleet record is the donor-independent copy
        self.sampling = sampling
        self.adapter = adapter


class ServingFleet:
    """Route requests over N fused engines; migrate them live; scale
    the fleet on SLO burn.

    ``engine_factory(engine_id, metrics_registry)`` builds one fused,
    NOT-started engine per call (the fleet pumps them; a background
    loop would race its lock discipline).  ``engines`` initial
    replicas; autoscaling moves the count inside
    [``min_engines``, ``max_engines``].  ``slo`` is the FLEET tracker
    (front-observed latency — per-engine trackers stay per-engine);
    defaults to :func:`DEFAULT_OBJECTIVES` when ``autoscale=True``.
    ``affinity_min_blocks`` is the smallest digest match worth
    overriding load placement for; ``affinity_probe_blocks`` caps the
    chain walk per candidate (routing stays O(probe · engines) per
    submit, independent of prompt length)."""

    def __init__(self, engine_factory, *, engines: int = 2,
                 min_engines: int = 1, max_engines: Optional[int] = None,
                 clock=None, metrics: Optional[MetricsRegistry] = None,
                 slo: Optional[SLOTracker] = None,
                 autoscale: bool = False, scale_dwell_ticks: int = 3,
                 scale_clear_ticks: int = 6,
                 scale_down_util: float = 0.5,
                 affinity_min_blocks: int = 1,
                 affinity_probe_blocks: int = 16):
        if int(engines) < 1:
            raise InvalidArgumentError(
                "a fleet needs at least one engine, got engines=%r"
                % (engines,))
        if int(min_engines) < 1 or int(min_engines) > int(engines):
            raise InvalidArgumentError(
                "need 1 <= min_engines <= engines, got min=%r "
                "engines=%r" % (min_engines, engines))
        max_engines = int(engines) if max_engines is None \
            else int(max_engines)
        if max_engines < int(engines):
            raise InvalidArgumentError(
                "need max_engines >= engines, got max=%r engines=%r"
                % (max_engines, engines))
        if int(scale_dwell_ticks) < 1 or int(scale_clear_ticks) < 1:
            raise InvalidArgumentError(
                "scale_dwell_ticks and scale_clear_ticks must be >= 1")
        self._clock = clock if clock is not None else time.monotonic
        self._factory = engine_factory
        self.min_engines = int(min_engines)
        self.max_engines = max_engines
        self._autoscale = bool(autoscale)
        self._scale_dwell = int(scale_dwell_ticks)
        self._scale_clear = int(scale_clear_ticks)
        self._scale_down_util = float(scale_down_util)
        self._affinity_min = int(affinity_min_blocks)
        self._probe_blocks = int(affinity_probe_blocks)
        self._slo = slo if slo is not None else (
            SLOTracker(DEFAULT_OBJECTIVES()) if autoscale else None)
        # PR 12 dwell/clear discipline at fleet scope; the init spawns
        # below zero this, so the controller waits a FULL dwell from
        # birth before its first action — a fleet cannot flap in its
        # first ticks
        self._as_ticks_since_change = 1 << 30
        self._as_clean_ticks = 0
        self._draining = False
        self._ticks = 0
        self._next_eid = 0
        self._next_rid = 0
        self._handles: Dict[str, _EngineHandle] = {}
        self._records: Dict[object, _FleetRecord] = {}
        # fleet-level adapter registry (docs §5q): {idx: weights}.
        # register_adapter() hot-loads onto every active engine and
        # every later spawn; the router only places adapter traffic on
        # engines that hold (or can hot-load) the row, and migration
        # hot-loads on the adopter before the hand-off
        self._adapters: Dict[int, dict] = {}

        self.metrics = metrics if metrics is not None \
            else MetricsRegistry()
        m = self.metrics
        self._c_submitted = m.counter(
            "serving_requests_submitted_total",
            "requests admitted at the fleet front")
        self._c_migrations = m.counter(
            "fleet_migrations_total",
            "live requests moved between engines (graceful drain or "
            "engine-death replay)")
        self._c_deaths = m.counter(
            "fleet_engine_deaths_total",
            "engines abandoned after a fatal pump error, a wedged/dead "
            "health probe, or hard_abandon()")
        self._c_scale_ups = m.counter(
            "fleet_scale_ups_total",
            "engines spawned by the SLO-burn controller")
        self._c_scale_downs = m.counter(
            "fleet_scale_downs_total",
            "engines retired by the SLO-clear controller")
        self._g_engines = m.gauge(
            "fleet_engines", "active engines right now")
        self._h_ttft = m.histogram(
            "serving_ttft_seconds",
            "front-observed submit-to-first-token latency "
            "(end-to-end: includes routing and any migration wait)")
        self._h_itl = m.histogram(
            "serving_inter_token_seconds",
            "front-observed gap between consecutive tokens (a "
            "migration's adoption gap rides the first post-migration "
            "token)")
        # labeled series (reason=affinity|load) live OUTSIDE the
        # registry — it is deliberately label-free — and are rendered
        # by render_prometheus() alongside it
        self._routed: Dict[str, Counter] = {
            reason: Counter("fleet_requests_routed_total")
            for reason in ("affinity", "load")}
        if self._slo is not None:
            self._slo.bind_metrics(m)

        for _ in range(int(engines)):
            self._spawn_engine(reason="init")

    # -- engine lifecycle ------------------------------------------------
    def _active_handles(self) -> List[_EngineHandle]:
        return [h for h in self._handles.values() if h.state == "active"]

    def _spawn_engine(self, reason: str) -> _EngineHandle:
        eid = "e%d" % self._next_eid
        self._next_eid += 1
        registry = MetricsRegistry()
        engine = self._factory(eid, registry)
        role = getattr(engine, "role", None)
        if role != "fused":
            raise InvalidArgumentError(
                "engine_factory must build fused-role engines (the "
                "fleet migrates requests among PEERS, not across tier "
                "roles) — %r returned role=%r" % (eid, role))
        if engine.is_running():
            raise InvalidArgumentError(
                "engine_factory must return a NOT-started engine: the "
                "fleet pumps its engines itself (engine %r has a "
                "background loop)" % (eid,))
        for idx, weights in self._adapters.items():
            # a replacement/scale-up engine serves the same adapter
            # traffic as its peers from its first tick — an in-place
            # bank write per adapter, never a recompile
            engine.load_adapter(idx, weights)
        h = _EngineHandle(eid, engine, registry, self._ticks)
        self._handles[eid] = h
        self._as_ticks_since_change = 0
        self._g_engines.set(len(self._active_handles()))
        if reason != "init":
            self._c_scale_ups.inc()
        trace.instant("fleet.spawn", engine=eid, reason=reason)
        slog.emit("fleet.spawn", engine=eid, reason=reason,
                  engines=len(self._active_handles()))
        return h

    def hard_abandon(self, engine_id, error: str = "hard-abandoned"
                     ) -> List[object]:
        """Operator/chaos seam: declare one engine dead RIGHT NOW (no
        waiting for its next pump to fail) and migrate its live
        requests onto survivors.  Returns the migrated rids."""
        with_lock = self._handles.get(engine_id)
        if with_lock is None:
            raise NotFoundError(
                "engine %r is not in the fleet" % (engine_id,))
        return self._on_engine_death(with_lock, RuntimeError(error))

    def _on_engine_death(self, h: _EngineHandle,
                         exc: BaseException) -> List[object]:
        """An engine is gone (pump raised through its own recovery, its
        health probe reports wedged/loop-dead, or the operator said
        so): replay its live requests onto survivors from the FLEET's
        token records.  The dead engine's stream queues are NOT
        drained — tokens it delivered after the fleet's last forward
        are exactly the window a crash may or may not have persisted,
        and greedy decode regenerates them byte-identically anyway —
        so the resume point is crash-honest by construction."""
        if h.state in ("dead", "retired"):
            return []
        h.state = "dead"
        self._c_deaths.inc()
        self._g_engines.set(len(self._active_handles()))
        victims = [r for r in self._records.values()
                   if r.engine_id == h.engine_id]
        trace.instant("fleet.engine_dead", engine=h.engine_id,
                      victims=len(victims), error=str(exc)[:200])
        slog.emit("fleet.engine_dead", engine=h.engine_id,
                  victims=len(victims), error=str(exc)[:200],
                  engines=len(self._active_handles()))
        migrated = []
        if len(self._active_handles()) < self.min_engines \
                and len(self._handles) - 1 < 4 * self.max_engines:
            # keep the floor: a fleet scaled to min cannot lose its
            # last engines to a crash and stay a fleet (the spawn cap
            # bounds a crash-looping factory)
            try:
                self._spawn_engine(reason="replace-dead")
            except Exception:  # noqa: BLE001 - survivors still adopt
                pass
        for rec in victims:
            target = self._pick_adopter(rec)
            if target is None:
                self._finalize_front(
                    rec, RequestState.FAILED, "error",
                    error="engine %r died and no healthy engine "
                          "remains to adopt %r"
                          % (h.engine_id, rec.rid))
                continue
            try:
                self._adopt_onto(rec, target, reason="engine-death")
                migrated.append(rec.rid)
            except Exception as adopt_exc:  # noqa: BLE001 - per-victim
                self._finalize_front(
                    rec, RequestState.FAILED, "error",
                    error="migration of %r off dead engine %r failed: "
                          "%s" % (rec.rid, h.engine_id,
                                  str(adopt_exc)[:200]))
        return migrated

    def retire_engine(self, engine_id, reason: str = "scale-down"
                      ) -> dict:
        """Gracefully drain one engine out of the fleet: checkpoint its
        journal (when it has one), migrate every live request to a peer
        through the preempt→detach→adopt file path (resubmit fallback),
        then shut it down.  Zero tokens dropped, zero recompiles on the
        file path.  Returns ``{"engine_id", "migrated",
        "adopted_from_file"}``."""
        h = self._handles.get(engine_id)
        if h is None:
            raise NotFoundError(
                "engine %r is not in the fleet" % (engine_id,))
        if h.state != "active":
            raise PreconditionNotMetError(
                "engine %r is %s — only an active engine can retire"
                % (engine_id, h.state))
        others = [x for x in self._active_handles() if x is not h]
        victims = [r for r in self._records.values()
                   if r.engine_id == engine_id]
        if victims and not others:
            raise PreconditionNotMetError(
                "cannot retire %r: it holds %d live request(s) and no "
                "other active engine exists to adopt them"
                % (engine_id, len(victims)))
        h.state = "draining"
        if getattr(h.engine, "_journal", None) is not None:
            # durability first: if THIS process dies mid-drain, the
            # compacted journal replays whatever had not migrated yet
            try:
                h.engine.checkpoint()
            except Exception:  # noqa: BLE001 - drain proceeds without
                pass
        from_file = 0
        for rec in victims:
            target = self._pick_adopter(rec)
            from_file += int(self._migrate_record(rec, target,
                                                  reason=reason))
        h.state = "retired"
        try:
            h.engine.shutdown(drain=False)
        except Exception:  # noqa: BLE001 - already drained of requests
            pass
        self._g_engines.set(len(self._active_handles()))
        trace.instant("fleet.retire", engine=engine_id, reason=reason,
                      migrated=len(victims))
        slog.emit("fleet.retire", engine=engine_id, reason=reason,
                  migrated=len(victims), adopted_from_file=from_file,
                  engines=len(self._active_handles()))
        return {"engine_id": engine_id, "migrated": len(victims),
                "adopted_from_file": from_file}

    # -- multi-LoRA adapter registry (docs §5q) --------------------------
    def register_adapter(self, idx: int, weights: dict) -> None:
        """Register adapter ``idx`` fleet-wide: hot-load its weights
        onto every active engine NOW (in-place bank writes — zero
        recompiles, ``cost_version()`` unchanged) and onto every later
        spawn, and keep the weights so migration can hot-load an
        adopter that missed the broadcast.  Typed errors propagate from
        the first engine that refuses (no attached bank, bad idx/key/
        shape) — the registry only records a load the fleet proved."""
        for h in self._active_handles():
            if not h.engine.has_adapter(idx) \
                    or idx not in self._adapters:
                h.engine.load_adapter(idx, weights)
        self._adapters[idx] = weights
        trace.instant("fleet.adapter_load", adapter=int(idx),
                      engines=len(self._active_handles()))
        slog.emit("fleet.adapter_load", adapter=int(idx),
                  engines=len(self._active_handles()))

    def unregister_adapter(self, idx: int) -> None:
        """Drop adapter ``idx`` fleet-wide: every engine's bank row is
        zeroed (each refuses, typed, while a live request is pinned to
        it) and the registry forgets the weights."""
        for h in self._active_handles():
            if h.engine.has_adapter(idx):
                h.engine.unload_adapter(idx)
        self._adapters.pop(int(idx), None)
        slog.emit("fleet.adapter_unload", adapter=int(idx))

    @property
    def adapters(self) -> tuple:
        """Registered adapter ids, ascending."""
        return tuple(sorted(self._adapters))

    def _ensure_adapter(self, h: _EngineHandle, adapter: int) -> bool:
        """True when ``h`` can serve ``adapter`` — already holding the
        row, or hot-loadable from the registry right now (the
        migration/routing fallback the §5q contract names)."""
        adapter = int(adapter)
        if adapter == 0 or h.engine.has_adapter(adapter):
            return True
        weights = self._adapters.get(adapter)
        if weights is None:
            return False
        try:
            h.engine.load_adapter(adapter, weights)
        except Exception:  # noqa: BLE001 - candidate disqualified
            return False
        trace.instant("fleet.adapter_hotload", adapter=adapter,
                      engine=h.engine_id)
        return True

    # -- migration mechanics ---------------------------------------------
    def _pick_adopter(self, rec: _FleetRecord
                      ) -> Optional[_EngineHandle]:
        """Choose the peer to move ``rec`` onto: affinity over the full
        resume point (prompt + committed tokens — the adopter
        re-prefills exactly that on the resubmit path), else least
        loaded; never the current owner.  An adapter-pinned request
        only lands where its bank row is servable — resident already,
        or hot-loaded from the fleet registry at the pick."""
        ids = rec.prompt if not rec.tokens else np.concatenate(
            [rec.prompt, np.asarray(rec.tokens, np.int32)])
        ranked = self._ranked_candidates(ids,
                                         exclude={rec.engine_id})
        for h, _reason, _matched in ranked:
            if self._ensure_adapter(h, rec.adapter):
                return h
        return None

    def _migrate_record(self, rec: _FleetRecord,
                        target: Optional[_EngineHandle],
                        reason: str) -> bool:
        """Graceful migration of one live request (caller holds the
        invariant that ``target`` is not the owner).  Drains the donor
        stream FIRST — everything the donor committed reaches the
        caller before the hand-off, so the fleet record and the donor's
        journal agree on the resume point — then donor ``migrate_out``
        → peer ``adopt_migration``.  True when the K/V file was
        adopted (vs prompt+committed resubmit)."""
        donor = self._handles[rec.engine_id]
        self._forward(rec, rec.engine_stream)
        entry = donor.engine.migrate_out(rec.rid)
        if target is None:
            self._finalize_front(
                rec, RequestState.FAILED, "error",
                error="no healthy engine to adopt %r during %s"
                      % (rec.rid, reason))
            return False
        return self._adopt_onto(rec, target, reason=reason,
                                entry=entry)

    def _adopt_onto(self, rec: _FleetRecord, target: _EngineHandle,
                    reason: str, entry: Optional[dict] = None) -> bool:
        """Point ``rec`` at ``target``: adopt from the donor's entry
        (graceful path) or from the fleet's own token record (death
        path — the donor cannot be asked anything)."""
        src = rec.engine_id
        if entry is None:
            entry = {"rid": rec.rid, "prompt": rec.prompt,
                     "tokens": list(rec.tokens),
                     "max_new": rec.max_new,
                     "priority": rec.priority, "tenant": rec.tenant,
                     "deadline_abs": rec.deadline_abs,
                     "sampling": rec.sampling,
                     "adapter": rec.adapter}
        adapter = int(entry.get("adapter") or 0)
        if adapter and not self._ensure_adapter(target, adapter):
            raise PreconditionNotMetError(
                "engine %r cannot serve adapter %d (no resident bank "
                "row and no registry weights to hot-load) — the "
                "migration of %r needs an adapter-capable adopter"
                % (target.engine_id, adapter, rec.rid))
        res = target.engine.adopt_migration(
            entry["rid"], entry["prompt"], entry["tokens"],
            entry["max_new"], priority=entry["priority"],
            tenant=entry["tenant"],
            deadline_abs=entry["deadline_abs"],
            sampling=entry.get("sampling"),
            adapter=adapter)
        rec.engine_stream = res["stream"]
        rec.engine_id = target.engine_id
        rec.migrations += 1
        self._c_migrations.inc()
        trace.instant("fleet.migrate", rid=rec.rid, src=src,
                      dst=target.engine_id, reason=reason,
                      adopted_from_file=res["adopted_from_file"])
        slog.emit("fleet.migrate", rid=rec.rid, src=src,
                  dst=target.engine_id, reason=reason,
                  adopted_from_file=res["adopted_from_file"],
                  committed_tokens=len(entry["tokens"]))
        return bool(res["adopted_from_file"])

    # -- routing ---------------------------------------------------------
    def _refresh_digest(self, h: _EngineHandle) -> Optional[dict]:
        since = h.digest["epoch"] if h.digest is not None else None
        d = h.engine.resident_prefix_digest(since_epoch=since)
        if d is None:
            h.digest = None
        elif "keys" in d:
            h.digest = d
        return h.digest

    def _affinity_blocks(self, h: _EngineHandle, ids) -> int:
        """Consecutive head blocks of ``ids`` resident in ``h``'s
        prefix index — the router-side replay of the pool's
        ``_match_prefix`` chain (same ``hash((parent, block_tokens))``
        keys, minus the token-equality collision check: a collision
        mis-ROUTES at worst, it can never mis-SERVE)."""
        d = self._refresh_digest(h)
        if not d or not d.get("keys"):
            return 0
        bs = d["block_size"]
        keys = d["keys"]
        matched = 0
        key = None
        # the final prompt position is never matched pool-side, so the
        # router walks the same (len-1)//bs limit
        limit = min((len(ids) - 1) // bs, self._probe_blocks)
        for j in range(limit):
            toks = tuple(int(t) for t in ids[j * bs:(j + 1) * bs])
            key = hash((key, toks))
            if key not in keys:
                break
            matched += 1
        return matched

    def _load_score(self, h: _EngineHandle, health: dict) -> float:
        """Smaller is better: backlog per slot, plus the engine's own
        distress signals (degradation rung, active SLO burn alerts) as
        additive penalties — backpressure read as data, the way the
        open item specifies."""
        slots = max(1, h.engine._pool.slots)
        score = (health["live_requests"] + health["queue_depth"]) \
            / float(slots)
        score += float(health.get("degraded") or 0)
        slo = health.get("slo")
        if slo:
            score += 2.0 * slo.get("alerts_active", 0)
        return score

    def _ranked_candidates(self, ids, exclude=frozenset()):
        """Healthy active engines best-first:
        ``[(handle, reason, matched_blocks), ...]``."""
        scored = []
        for h in self._active_handles():
            if h.engine_id in exclude:
                continue
            hs = h.engine.health()
            if hs["state"] in ("wedged", "loop-dead", "stopped",
                               "draining", "restoring"):
                continue
            matched = self._affinity_blocks(h, ids)
            load = self._load_score(h, hs)
            scored.append((h, matched, load))
        affine = [s for s in scored if s[1] >= self._affinity_min]
        if affine:
            affine.sort(key=lambda s: (-s[1], s[2]))
            rest = sorted((s for s in scored
                           if s[1] < self._affinity_min),
                          key=lambda s: s[2])
            return [(h, "affinity", m) for h, m, _ in affine] \
                + [(h, "load", m) for h, m, _ in rest]
        scored.sort(key=lambda s: s[2])
        return [(h, "load", m) for h, m, _ in scored]

    # -- admission -------------------------------------------------------
    def submit(self, input_ids, max_new_tokens: int, request_id=None,
               deadline_s: Optional[float] = None, priority=0,
               tenant=None, temperature=None, top_k=None, top_p=None,
               seed=None, adapter: int = 0) -> ResponseStream:
        """Admit one request somewhere in the fleet; returns the
        FRONT's stream — tokens keep flowing on this one handle across
        any number of migrations.  Candidates are tried best-first:
        a retryable per-engine rejection (queue full, deadline
        estimate, tightened admission) falls through to the next
        engine, and only when EVERY engine refuses does the last typed
        error propagate — fleet admission control is the union of the
        engines' own.  Auto request-ids are fleet-assigned (``"f0"``,
        ``"f1"``, ...): N engines each minting their own integers
        would collide in the shared spill directory.

        ``temperature``/``top_k``/``top_p``/``seed`` are this request's
        sampling config and ``adapter`` its LoRA id (docs §5q), passed
        through to the owning engine; adapter traffic is only placed on
        engines holding (or hot-loading, from the fleet registry) the
        bank row, and both ride the fleet record so migration keeps
        serving the same stream under the same adapter."""
        if self._draining:
            raise PreconditionNotMetError(
                "fleet front is draining/shut down")
        if request_id is not None and request_id in self._records:
            raise DuplicateRequestError(
                "request_id %r is already live on the fleet"
                % (request_id,))
        ids = np.asarray(getattr(input_ids, "value", input_ids))
        rid = request_id
        if rid is None:
            while True:
                rid = "f%d" % self._next_rid
                self._next_rid += 1
                if rid not in self._records:
                    break
        ranked = self._ranked_candidates(ids)
        if not ranked:
            raise QueueFullError(
                "no healthy active engine in the fleet; back off and "
                "retry")
        adapter = int(adapter)
        if adapter:
            placeable = [c for c in ranked
                         if self._ensure_adapter(c[0], adapter)]
            if not placeable:
                raise InvalidArgumentError(
                    "adapter %d is not servable anywhere in the fleet "
                    "(no engine holds the bank row and the fleet "
                    "registry has no weights for it — "
                    "register_adapter(%d, weights) first)"
                    % (adapter, adapter))
            ranked = placeable
        last_exc = None
        for h, reason, matched in ranked:
            try:
                es = h.engine.submit(ids, max_new_tokens,
                                     request_id=rid,
                                     deadline_s=deadline_s,
                                     priority=priority, tenant=tenant,
                                     temperature=temperature,
                                     top_k=top_k, top_p=top_p,
                                     seed=seed, adapter=adapter)
            except (UnavailableError, PreconditionNotMetError) as e:
                # retryable per-engine refusal (queue full, deadline
                # estimate, tightened admission, draining): the next
                # candidate gets its shot
                last_exc = e
                continue
            now = self._clock()
            stream = ResponseStream(self, rid, int(max_new_tokens))
            eng_rec = h.engine._live.get(rid)
            self._records[rid] = _FleetRecord(
                rid, stream, h.engine_id, es, ids,
                int(max_new_tokens), now, priority, tenant,
                None if deadline_s is None else now + float(deadline_s),
                # the ENGINE resolved the config (seed included) at its
                # admission edge; the fleet copies it so the death path
                # can re-adopt without asking a dead donor
                sampling=(None if eng_rec is None
                          else eng_rec.sampling),
                adapter=adapter)
            self._c_submitted.inc()
            self._routed[reason].inc()
            trace.instant("fleet.route", rid=rid, engine=h.engine_id,
                          reason=reason, matched_blocks=matched)
            slog.emit("fleet.route", rid=rid, engine=h.engine_id,
                      reason=reason, matched_blocks=matched,
                      prompt_tokens=int(ids.shape[0]))
            return stream
        raise last_exc

    # -- forwarding ------------------------------------------------------
    def _forward(self, rec: _FleetRecord, src: ResponseStream) -> bool:
        """Drain one engine stream's queue into the front stream; True
        when the engine delivered its terminal."""
        while True:
            try:
                item = src._q.get_nowait()
            except Exception:  # queue.Empty
                return False
            if item is _TERMINAL:
                return True
            now = self._clock()
            if rec.first_t is None:
                rec.first_t = now
                self._h_ttft.observe(now - rec.submit_t)
                if self._slo is not None:
                    self._slo.observe_latency("ttft",
                                              now - rec.submit_t)
            else:
                self._h_itl.observe(now - rec.last_t)
                if self._slo is not None:
                    self._slo.observe_latency("inter_token",
                                              now - rec.last_t)
            rec.last_t = now
            rec.tokens.append(int(item))
            rec.stream._put_token(int(item))

    def _finalize_front(self, rec: _FleetRecord, state: str, reason,
                        error=None) -> None:
        now = self._clock()
        toks = np.asarray(rec.tokens, np.int32)
        if self._slo is not None:
            self._slo.observe_terminal(state)
        trace.instant("req." + state.lower(), rid=rec.rid,
                      reason=reason, new_tokens=int(toks.size),
                      front=True, error=error)
        rec.stream._finalize(StreamStatus(
            request_id=rec.rid, state=state, finish_reason=reason,
            tokens=toks, prompt_tokens=rec.prompt_len,
            new_tokens=int(toks.size),
            ttft_s=(None if rec.first_t is None
                    else rec.first_t - rec.submit_t),
            total_s=now - rec.submit_t, error=error))
        self._records.pop(rec.rid, None)

    def _forward_all(self) -> None:
        for rec in list(self._records.values()):
            if self._forward(rec, rec.engine_stream):
                st = rec.engine_stream.status
                if st.state == RequestState.HANDED_OFF:
                    # the engine-side terminal of a migration the
                    # fleet itself ordered: the front stream rides on
                    continue
                self._finalize_front(rec, st.state, st.finish_reason,
                                     error=st.error)

    # -- drive (pump mode only, like every tier-1 test) ------------------
    def is_running(self) -> bool:
        """The front is pump-mode only (no background thread): the
        caller — or the stream iterating — is the fleet's legs."""
        return False

    def pump(self, steps: int = 1) -> bool:
        """One fleet tick per step: every live engine ticks once
        (an exception escaping an engine's own recovery, or a
        wedged/dead health probe, declares it dead and migrates its
        requests), tokens forward to the front streams, the SLO
        windows roll, and the autoscale controller evaluates.  True
        while front-live requests remain."""
        for _ in range(int(steps)):
            self._ticks += 1
            for h in list(self._handles.values()):
                if h.state not in ("active", "draining"):
                    continue
                try:
                    h.engine.pump(1)
                except Exception as e:  # noqa: BLE001 - engine-fatal
                    self._on_engine_death(h, e)
                    continue
                hs = h.engine.health()
                if hs["state"] in ("wedged", "loop-dead"):
                    self._on_engine_death(
                        h, RuntimeError("health probe reports %r"
                                        % (hs["state"],)))
            self._forward_all()
            if self._slo is not None:
                self._slo.note_tick()
            self._autoscale_eval()
            if not self._records:
                break
        return bool(self._records)

    # -- autoscaling -----------------------------------------------------
    def _utilization(self) -> float:
        act = self._active_handles()
        slots = sum(h.engine._pool.slots for h in act)
        if not slots:
            return 1.0
        return len(self._records) / float(slots)

    def _autoscale_eval(self) -> None:
        """The PR 12 dwell/clear discipline at fleet scope: scale UP
        one engine per sustained multiwindow burn alert once ``dwell``
        ticks passed since the last change; scale DOWN (graceful
        retire of the least-loaded engine) after ``clear`` consecutive
        alert-free ticks with utilization under the floor."""
        if not self._autoscale or self._slo is None or self._draining:
            return
        alerting = self._slo.alerting_names()
        self._as_ticks_since_change += 1
        active = self._active_handles()
        if alerting:
            self._as_clean_ticks = 0
            if len(active) < self.max_engines \
                    and self._as_ticks_since_change >= self._scale_dwell:
                self._spawn_engine(
                    reason="slo-burn:" + ",".join(sorted(alerting)))
        else:
            self._as_clean_ticks += 1
            if len(active) > self.min_engines \
                    and self._as_clean_ticks >= self._scale_clear \
                    and self._utilization() <= self._scale_down_util:
                victim = min(
                    active, key=lambda h: sum(
                        1 for r in self._records.values()
                        if r.engine_id == h.engine_id))
                self._c_scale_downs.inc()
                self.retire_engine(victim.engine_id,
                                   reason="slo-clear")
                self._as_clean_ticks = 0
                self._as_ticks_since_change = 0

    # -- lifecycle -------------------------------------------------------
    def cancel(self, request_id) -> bool:
        """Cancel wherever the request lives; the front stream ends
        CANCELLED.  Idempotent."""
        rec = self._records.get(request_id)
        if rec is None:
            return False
        h = self._handles.get(rec.engine_id)
        if h is not None and h.state not in ("dead", "retired"):
            try:
                h.engine.cancel(request_id)
            except Exception:  # noqa: BLE001 - front terminal wins
                pass
        self._finalize_front(rec, RequestState.CANCELLED, "cancelled")
        return True

    def request_state(self, request_id) -> Optional[str]:
        """Front-perspective lifecycle state (the stream handle's
        ``.state``)."""
        rec = self._records.get(request_id)
        if rec is None:
            return None
        h = self._handles.get(rec.engine_id)
        if h is None or h.state in ("dead", "retired"):
            return RequestState.PREEMPTED
        return h.engine.request_state(request_id) \
            or RequestState.DECODING

    def drain(self, timeout_s: Optional[float] = None) -> bool:
        """Stop admissions, pump until every front-live request
        terminates; False on timeout (wall clock, like the engines)."""
        self._draining = True
        deadline = None if timeout_s is None \
            else time.monotonic() + timeout_s
        while self._records:
            self.pump(1)
            if deadline is not None and time.monotonic() >= deadline:
                return False
        return True

    def shutdown(self, drain: bool = True) -> None:
        """Graceful stop: drain (or cancel) front-live requests, then
        shut every non-retired engine down (journals flushed and
        closed)."""
        if drain:
            self.drain()
        else:
            self._draining = True
            for rid in list(self._records):
                self.cancel(rid)
        self._draining = True
        for h in self._handles.values():
            if h.state in ("retired",):
                continue
            try:
                h.engine.shutdown(drain=False)
            except Exception:  # noqa: BLE001 - dead engines stay dead
                pass

    # -- observability ---------------------------------------------------
    def health(self) -> dict:
        """Aggregated probe body: healthy while at least one active
        engine is (the fleet can still serve), with every engine's own
        snapshot nested under its id and the fleet surfaces on top —
        what the fleet-aware ``GET /healthz`` serves."""
        per = {}
        for eid, h in self._handles.items():
            if h.state == "retired":
                per[eid] = {"healthy": False, "state": "retired"}
            elif h.state == "dead":
                per[eid] = {"healthy": False, "state": "dead"}
            else:
                eh = h.engine.health()
                if h.state == "draining":
                    eh = dict(eh)
                    eh["state"] = "draining"
                per[eid] = eh
        active = self._active_handles()
        healthy = (not self._draining and any(
            per[h.engine_id]["healthy"] for h in active))
        out = {
            "healthy": healthy,
            "state": ("draining" if self._draining
                      else "serving" if self._records else "idle"),
            "live_requests": len(self._records),
            "active_engines": len(active),
            "engines_total": len(self._handles),
            "migrations": int(self._c_migrations.value),
            "engine_deaths": int(self._c_deaths.value),
            "engines": per,
        }
        if self._slo is not None:
            out["slo"] = self._slo.health_summary()
        return out

    def slo_snapshot(self) -> dict:
        """The fleet tracker's full state plus each engine's own
        (when it has one) — the aggregated ``GET /slo`` body."""
        if self._slo is None:
            raise PreconditionNotMetError(
                "no SLO tracker is configured on this fleet: pass "
                "slo=SLOTracker(...) (or autoscale=True) at "
                "construction")
        out = self._slo.snapshot()
        engines = {}
        for eid, h in self._handles.items():
            if h.state in ("dead", "retired"):
                continue
            try:
                engines[eid] = h.engine.slo_snapshot()
            except PreconditionNotMetError:
                continue
        out["engines"] = engines
        return out

    def request_trace(self, request_id) -> dict:
        """Delegate to the engine currently owning the request (live),
        else ask every engine that might remember it."""
        rec = self._records.get(request_id)
        order = []
        if rec is not None and rec.engine_id in self._handles:
            order.append(self._handles[rec.engine_id])
        order.extend(h for h in self._handles.values()
                     if h not in order and h.state not in ("retired",))
        last: BaseException = NotFoundError(
            "request_id %r is unknown to every engine in the fleet"
            % (request_id,))
        for h in order:
            try:
                return h.engine.request_trace(request_id)
            except Exception as e:  # noqa: BLE001 - try the next engine
                last = e
        raise last

    def flight_recorder(self) -> dict:
        """Per-engine flight-recorder tails keyed by engine id (only
        engines with an active tracer contribute)."""
        out = {}
        last = None
        for eid, h in self._handles.items():
            if h.state in ("retired",):
                continue
            try:
                out[eid] = h.engine.flight_recorder()
            except PreconditionNotMetError as e:
                last = e
        if not out and last is not None:
            raise last
        return out

    def compile_counts(self) -> dict:
        """Per-engine compile accounting keyed by engine id — the
        chaos pin: migration must not grow any survivor's counts."""
        return {eid: h.engine.compile_counts()
                for eid, h in self._handles.items()
                if h.state not in ("retired",)}

    def engine_states(self) -> dict:
        """``{engine_id: "active"|"draining"|"dead"|"retired"}``."""
        return {eid: h.state for eid, h in self._handles.items()}

    def engines(self) -> dict:
        """Live engine objects keyed by id (supervision fan-in and
        tests; not part of the request path)."""
        return {eid: h.engine for eid, h in self._handles.items()
                if h.state not in ("retired",)}

    def render_prometheus(self) -> str:
        """ONE scrape body for the whole fleet: the fleet registry's
        series unlabeled, the labeled routing counters, and every
        per-engine registry re-rendered under an ``engine`` label —
        grouped so each metric name gets exactly one TYPE header even
        when the fleet and N engines all register it (the
        double-counting fix the exposition round-trip test pins: a
        per-engine series NEVER appears unlabeled)."""
        groups: Dict[str, dict] = {}

        def add(name, kind, help_, labels, metric):
            g = groups.setdefault(
                name, {"kind": kind, "help": help_, "series": []})
            g["series"].append((labels, metric))

        for name, metric in self.metrics._metrics.items():
            add(name, metric.kind, metric.help, None, metric)
        for reason in sorted(self._routed):
            add("fleet_requests_routed_total", "counter",
                "requests placed by the router, by decision reason",
                'reason="%s"' % escape_label_value(reason),
                self._routed[reason])
        for eid in sorted(self._handles):
            h = self._handles[eid]
            lab = 'engine="%s"' % escape_label_value(str(eid))
            for name, metric in h.registry._metrics.items():
                add(name, metric.kind, metric.help, lab, metric)

        lines: List[str] = []
        for name, g in groups.items():
            if g["help"]:
                lines.append("# HELP %s %s"
                             % (name, escape_help(g["help"])))
            lines.append("# TYPE %s %s" % (name, g["kind"]))
            for labels, metric in g["series"]:
                if isinstance(metric, Histogram):
                    running = 0
                    for b, c in zip(metric.buckets, metric._counts):
                        running += c
                        lab = (('%s,le="%s"' % (labels, _fmt(b)))
                               if labels else 'le="%s"' % _fmt(b))
                        lines.append("%s_bucket{%s} %d"
                                     % (name, lab, running))
                    lab = (labels + ',le="+Inf"') if labels \
                        else 'le="+Inf"'
                    lines.append("%s_bucket{%s} %d"
                                 % (name, lab, metric.count))
                    suffix = ("{%s}" % labels) if labels else ""
                    lines.append("%s_sum%s %s"
                                 % (name, suffix, _fmt(metric.sum)))
                    lines.append("%s_count%s %d"
                                 % (name, suffix, metric.count))
                else:
                    suffix = ("{%s}" % labels) if labels else ""
                    lines.append("%s%s %s"
                                 % (name, suffix, _fmt(metric.value)))
        return "\n".join(lines) + "\n"

    @property
    def live_requests(self) -> int:
        return len(self._records)

    @property
    def draining(self) -> bool:
        return self._draining

    @property
    def slo(self):
        """The fleet's :class:`~.slo.SLOTracker` (None when off)."""
        return self._slo
