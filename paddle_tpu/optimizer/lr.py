"""LR schedulers (reference: python/paddle/optimizer/lr.py — LRScheduler:37
base + the 13 decay classes, see SURVEY.md A.4).
"""
from __future__ import annotations

import math
from typing import List, Optional


class LRScheduler:
    """Base class (lr.py:37): stateful step counter, state_dict round-trip."""

    def __init__(self, learning_rate: float = 0.1, last_epoch: int = -1, verbose: bool = False):
        self.base_lr = float(learning_rate)
        self.last_epoch = last_epoch
        self.verbose = verbose
        self.last_lr = self.base_lr
        self.step()

    def __call__(self) -> float:
        return self.last_lr

    def step(self, epoch: Optional[int] = None) -> None:
        if epoch is None:
            self.last_epoch += 1
        else:
            self.last_epoch = epoch
        self.last_lr = self.get_lr()
        if self.verbose:
            print("Epoch {}: {} set learning rate to {}.".format(self.last_epoch, type(self).__name__, self.last_lr))

    def get_lr(self) -> float:
        raise NotImplementedError

    _state_keys = ["last_epoch", "last_lr"]

    def state_dict(self) -> dict:
        return {k: getattr(self, k) for k in self._state_keys}

    def set_state_dict(self, state: dict) -> None:
        for k, v in state.items():
            if hasattr(self, k):
                setattr(self, k, v)

    set_dict = set_state_dict


class NoamDecay(LRScheduler):
    """lr.py:203 — lr = lr0 * d_model^-0.5 * min(n^-0.5, n * warmup^-1.5)."""

    def __init__(self, d_model: int, warmup_steps: int, learning_rate: float = 1.0, last_epoch: int = -1, verbose: bool = False):
        self.d_model = d_model
        self.warmup_steps = warmup_steps
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self) -> float:
        n = max(self.last_epoch, 1)
        return self.base_lr * (self.d_model ** -0.5) * min(n ** -0.5, n * (self.warmup_steps ** -1.5))


class PiecewiseDecay(LRScheduler):
    """lr.py:296."""

    def __init__(self, boundaries: List[int], values: List[float], last_epoch: int = -1, verbose: bool = False):
        self.boundaries = boundaries
        self.values = values
        super().__init__(values[0], last_epoch, verbose)

    def get_lr(self) -> float:
        for i, b in enumerate(self.boundaries):
            if self.last_epoch < b:
                return self.values[i]
        return self.values[len(self.boundaries)]


class NaturalExpDecay(LRScheduler):
    """lr.py:387 — lr = lr0 * exp(-gamma * epoch)."""

    def __init__(self, learning_rate: float, gamma: float, last_epoch: int = -1, verbose: bool = False):
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self) -> float:
        return self.base_lr * math.exp(-self.gamma * self.last_epoch)


class InverseTimeDecay(LRScheduler):
    """lr.py:466 — lr = lr0 / (1 + gamma * epoch)."""

    def __init__(self, learning_rate: float, gamma: float, last_epoch: int = -1, verbose: bool = False):
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self) -> float:
        return self.base_lr / (1 + self.gamma * self.last_epoch)


class PolynomialDecay(LRScheduler):
    """lr.py:547."""

    def __init__(self, learning_rate: float, decay_steps: int, end_lr: float = 0.0001,
                 power: float = 1.0, cycle: bool = False, last_epoch: int = -1, verbose: bool = False):
        self.decay_steps = decay_steps
        self.end_lr = end_lr
        self.power = power
        self.cycle = cycle
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self) -> float:
        step = self.last_epoch
        if self.cycle:
            div = math.ceil(step / float(self.decay_steps)) or 1
            decay_steps = self.decay_steps * div
        else:
            decay_steps = self.decay_steps
            step = min(step, self.decay_steps)
        return (self.base_lr - self.end_lr) * ((1 - float(step) / float(decay_steps)) ** self.power) + self.end_lr


class LinearWarmup(LRScheduler):
    """lr.py:667 — linear ramp into an inner schedule (or constant)."""

    def __init__(self, learning_rate, warmup_steps: int, start_lr: float, end_lr: float,
                 last_epoch: int = -1, verbose: bool = False):
        self.lr_scheduler = learning_rate if isinstance(learning_rate, LRScheduler) else None
        self.warmup_steps = warmup_steps
        self.start_lr = start_lr
        self.end_lr = end_lr
        base = learning_rate if isinstance(learning_rate, float) else float(end_lr)
        super().__init__(base, last_epoch, verbose)

    def get_lr(self) -> float:
        if self.last_epoch < self.warmup_steps:
            return (self.end_lr - self.start_lr) * self.last_epoch / float(self.warmup_steps) + self.start_lr
        if self.lr_scheduler is not None:
            self.lr_scheduler.step()
            return self.lr_scheduler()
        return self.base_lr

    def state_dict(self) -> dict:
        sd = super().state_dict()
        if self.lr_scheduler is not None:
            sd["LinearWarmup_LR"] = self.lr_scheduler.state_dict()
        return sd

    def set_state_dict(self, state: dict) -> None:
        inner = state.pop("LinearWarmup_LR", None)
        if inner is not None and self.lr_scheduler is not None:
            self.lr_scheduler.set_state_dict(inner)
        super().set_state_dict(state)


class ExponentialDecay(LRScheduler):
    """lr.py:804 — lr = lr0 * gamma^epoch."""

    def __init__(self, learning_rate: float, gamma: float, last_epoch: int = -1, verbose: bool = False):
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self) -> float:
        return self.base_lr * (self.gamma ** self.last_epoch)


class MultiStepDecay(LRScheduler):
    """lr.py:884."""

    def __init__(self, learning_rate: float, milestones: List[int], gamma: float = 0.1,
                 last_epoch: int = -1, verbose: bool = False):
        self.milestones = milestones
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self) -> float:
        n = sum(1 for m in self.milestones if self.last_epoch >= m)
        return self.base_lr * (self.gamma ** n)


class StepDecay(LRScheduler):
    """lr.py:994."""

    def __init__(self, learning_rate: float, step_size: int, gamma: float = 0.1,
                 last_epoch: int = -1, verbose: bool = False):
        self.step_size = step_size
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self) -> float:
        return self.base_lr * (self.gamma ** (self.last_epoch // self.step_size))


class LambdaDecay(LRScheduler):
    """lr.py:1095."""

    def __init__(self, learning_rate: float, lr_lambda, last_epoch: int = -1, verbose: bool = False):
        self.lr_lambda = lr_lambda
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self) -> float:
        return self.base_lr * self.lr_lambda(self.last_epoch)


class ReduceOnPlateau(LRScheduler):
    """lr.py:1183 — metric-driven; step(metric) instead of step()."""

    def __init__(self, learning_rate: float, mode: str = "min", factor: float = 0.1,
                 patience: int = 10, threshold: float = 1e-4, threshold_mode: str = "rel",
                 cooldown: int = 0, min_lr: float = 0.0, epsilon: float = 1e-8, verbose: bool = False):
        self.mode = mode
        self.factor = factor
        self.patience = patience
        self.threshold = threshold
        self.threshold_mode = threshold_mode
        self.cooldown = cooldown
        self.min_lr = min_lr
        self.epsilon = epsilon
        self.verbose = verbose
        self.base_lr = float(learning_rate)
        self.last_lr = self.base_lr
        self.last_epoch = 0
        self.best = None
        self.cooldown_counter = 0
        self.num_bad_epochs = 0

    _state_keys = ["last_epoch", "last_lr", "best", "cooldown_counter", "num_bad_epochs"]

    def _is_better(self, current, best) -> bool:
        if best is None:
            return True
        if self.threshold_mode == "rel":
            delta = self.threshold * abs(best)
        else:
            delta = self.threshold
        return current < best - delta if self.mode == "min" else current > best + delta

    def step(self, metrics=None, epoch=None) -> None:
        if metrics is None:
            return
        current = float(metrics)
        self.last_epoch += 1
        if self._is_better(current, self.best):
            self.best = current
            self.num_bad_epochs = 0
        else:
            self.num_bad_epochs += 1
        if self.cooldown_counter > 0:
            self.cooldown_counter -= 1
            self.num_bad_epochs = 0
        if self.num_bad_epochs > self.patience:
            new_lr = max(self.last_lr * self.factor, self.min_lr)
            if self.last_lr - new_lr > self.epsilon:
                self.last_lr = new_lr
                if self.verbose:
                    print("Epoch {}: ReduceOnPlateau set learning rate to {}.".format(self.last_epoch, new_lr))
            self.cooldown_counter = self.cooldown
            self.num_bad_epochs = 0

    def get_lr(self) -> float:
        return self.last_lr


class CosineAnnealingDecay(LRScheduler):
    """lr.py:1393."""

    def __init__(self, learning_rate: float, T_max: int, eta_min: float = 0.0,
                 last_epoch: int = -1, verbose: bool = False):
        self.T_max = T_max
        self.eta_min = eta_min
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self) -> float:
        return self.eta_min + (self.base_lr - self.eta_min) * (
            1 + math.cos(math.pi * self.last_epoch / self.T_max)
        ) / 2


class OneCycleLR(LRScheduler):
    """paddle 2.x incubate scheduler; included for completeness."""

    def __init__(self, max_learning_rate: float, total_steps: int, divide_factor: float = 25.0,
                 end_learning_rate: float = 1e-4, phase_pct: float = 0.3,
                 anneal_strategy: str = "cos", last_epoch: int = -1, verbose: bool = False):
        self.max_lr = max_learning_rate
        self.total_steps = total_steps
        self.initial_lr = max_learning_rate / divide_factor
        self.end_lr = end_learning_rate
        self.phase_pct = phase_pct
        self.up_steps = int(total_steps * phase_pct)
        super().__init__(self.initial_lr, last_epoch, verbose)

    def get_lr(self) -> float:
        step = min(self.last_epoch, self.total_steps)
        if step <= self.up_steps and self.up_steps > 0:
            pct = step / self.up_steps
            return self.initial_lr + (self.max_lr - self.initial_lr) * (1 - math.cos(math.pi * pct)) / 2
        down = self.total_steps - self.up_steps
        pct = (step - self.up_steps) / max(down, 1)
        return self.end_lr + (self.max_lr - self.end_lr) * (1 + math.cos(math.pi * pct)) / 2
