"""``paddle_tpu.optimizer`` — optimizers.

Reference parity: ``python/paddle/optimizer/`` (Adam/AdamW/Momentum/Lamb/...)
and the C++ update kernels ``paddle/fluid/operators/optimizers/*`` (adam_op.cc
multi-precision master weights, momentum_op, lamb_op, lars_momentum_op).

Design: every optimizer implements a **pure** per-parameter update
``_apply_one(val, grad, state, lr, p) -> (new_val, new_state)`` over raw
arrays.  ``step()`` runs it eagerly from ``p.grad``; the jitted train-step
path (paddle_tpu.jit.TrainStep) traces the very same function, so eager and
compiled training share one update rule — the TPU-native answer to the
reference's per-device optimizer kernels.
"""
from __future__ import annotations

import contextlib
from typing import Dict, List, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from ..core.errors import InvalidArgumentError
from ..framework.tensor import Parameter, Tensor
from ..regularizer import L1Decay, L2Decay, WeightDecayRegularizer
from . import lr as lr_sched
from .lr import LRScheduler

__all__ = [
    "Optimizer", "SGD", "Momentum", "Adam", "AdamW", "Adagrad", "Adadelta",
    "Adamax", "RMSProp", "Lamb", "Lars", "Ftrl", "Lookahead",
    "ModelAverage", "lr",
]

lr = lr_sched


class Optimizer:
    """Base optimizer (python/paddle/optimizer/optimizer.py parity)."""

    def __init__(
        self,
        learning_rate=0.001,
        parameters: Optional[Sequence[Parameter]] = None,
        weight_decay=None,
        grad_clip=None,
        multi_precision: bool = False,
        name: Optional[str] = None,
    ):
        if parameters is not None:
            parameters = list(parameters)
            for p in parameters:
                if not isinstance(p, Tensor):
                    raise InvalidArgumentError(
                        "optimizer parameters must be Tensors, got %r" % type(p)
                    )
        self._parameter_list = parameters
        self._learning_rate = learning_rate
        if isinstance(weight_decay, float):
            weight_decay = L2Decay(weight_decay)
        self._weight_decay = weight_decay
        self._grad_clip = grad_clip
        self._multi_precision = multi_precision
        self._states: Dict[str, dict] = {}
        self._name = name or type(self).__name__

    # -- lr ---------------------------------------------------------------
    def get_lr(self) -> float:
        if isinstance(self._learning_rate, LRScheduler):
            return float(self._learning_rate())
        return float(self._learning_rate)

    def set_lr(self, value: float) -> None:
        if isinstance(self._learning_rate, LRScheduler):
            raise InvalidArgumentError("cannot set_lr when using an LRScheduler")
        self._learning_rate = float(value)

    @property
    def _param_groups(self):
        return self._parameter_list

    # -- state ------------------------------------------------------------
    def _state_for(self, p: Parameter) -> dict:
        key = p.name
        if key not in self._states:
            self._states[key] = self._init_state(p)
        return self._states[key]

    def _init_state(self, p: Parameter) -> dict:
        state: dict = {}
        if self._multi_precision and p.value.dtype != jnp.float32:
            state["master_weight"] = p.value.astype(jnp.float32)
        return state

    def _master(self, val, state):
        return state.get("master_weight", val)

    def _finish(self, new_master, val_dtype, state):
        """Write back master weight; return the model-dtype value."""
        if "master_weight" in state:
            state = dict(state, master_weight=new_master)
            return new_master.astype(val_dtype), state
        return new_master, state

    # -- the update -------------------------------------------------------
    def _apply_one(self, val, grad, state, lr, p):  # pragma: no cover - abstract
        raise NotImplementedError

    def _regularized(self, p, val, grad):
        reg = p.regularizer if getattr(p, "regularizer", None) is not None else self._weight_decay
        if isinstance(reg, WeightDecayRegularizer):
            return reg(val.astype(grad.dtype), grad)
        return grad

    @property
    def _decoupled_decay(self) -> bool:
        return False  # AdamW overrides

    def step(self) -> None:
        if self._parameter_list is None:
            raise InvalidArgumentError(
                "this optimizer was constructed without a parameters list; "
                "pass parameters=model.parameters()"
            )
        from ..framework.sparse import SparseGrad

        params_grads = [
            (p, p._grad_val)
            for p in self._parameter_list
            if not p.stop_gradient and p._grad_val is not None
        ]
        if self._grad_clip is not None:
            # norm-based clipping needs real norms: densify sparse grads
            params_grads = [
                (p, g.to_dense() if isinstance(g, SparseGrad) else g)
                for p, g in params_grads]
            params_grads = self._grad_clip(params_grads)
        lr_val = self.get_lr()
        for p, g in params_grads:
            if g is None:
                continue
            state = self._state_for(p)
            plr = lr_val * p.optimize_attr.get("learning_rate", 1.0)
            if isinstance(g, SparseGrad):
                # SelectedRows consumer (adam_op lazy_mode / sgd_op
                # SelectedRows branch): row-slice update when the optimizer
                # supports it, dense scatter otherwise
                if self._supports_sparse(p, state):
                    g = g.coalesce()
                    new_val, new_state = self._apply_one_sparse(
                        p.value, g, state, plr, p)
                    self._states[p.name] = new_state
                    p._replace_value(new_val)
                    continue
                g = g.to_dense()
            if not self._decoupled_decay:
                g = self._regularized(p, p.value, g)
            new_val, new_state = self._apply_one(p.value, g, state, plr, p)
            self._states[p.name] = new_state
            p._replace_value(new_val)

    def _supports_sparse(self, p, state) -> bool:
        return False

    def _apply_one_sparse(self, val, grad, state, lr, p):
        raise NotImplementedError  # pragma: no cover - gated by _supports_sparse

    def _functional_step(self, params, vals, grads, states, lr_val):
        """Pure update over raw arrays — the jitted train-step path.

        Same update rule as :meth:`step` (clip → regularize → _apply_one) but
        with values/grads/states threaded explicitly so ``jax.jit`` can trace
        and donate them.  Returns (new_vals, new_states).

        Semantics delta vs eager: ``jax.grad`` produces *dense* gradients, so
        a parameter unused by the loss receives a zero grad and still goes
        through the update (decay/moment bookkeeping apply), whereas eager
        ``step()`` skips params whose ``.grad`` is None.  This matches the
        reference's static-graph/DataParallel behavior, not its dygraph one.
        """
        params_grads = list(zip(params, grads))
        if self._grad_clip is not None:
            params_grads = self._grad_clip(params_grads)
        new_vals, new_states = [], []
        for (p, g), val, state in zip(params_grads, vals, states):
            if g is None:
                new_vals.append(val)
                new_states.append(state)
                continue
            if not self._decoupled_decay:
                g = self._regularized(p, val, g)
            plr = lr_val * p.optimize_attr.get("learning_rate", 1.0)
            nv, ns = self._apply_one(val, g, state, plr, p)
            new_vals.append(nv)
            new_states.append(ns)
        return new_vals, new_states

    def clear_grad(self, set_to_zero: bool = False) -> None:
        if self._parameter_list is None:
            return
        for p in self._parameter_list:
            p.clear_grad()

    clear_gradients = clear_grad

    def minimize(self, loss, startup_program=None, parameters=None, no_grad_set=None):
        """Dygraph minimize: backward + step (fleet_base.py:1288 single-proc).

        Static-graph loss (a ``paddle.static.Variable``): append grad +
        update nodes to the loss's program; ``Executor.run`` applies them
        (the GradientDescent/Adam op insertion of fluid/optimizer.py)."""
        from ..static.graph import Variable as _StaticVar

        if isinstance(loss, _StaticVar):
            return self._minimize_static(loss, parameters)
        if loss._node is not None:
            loss.backward()
        self.step()
        return None, None

    def _minimize_static(self, loss, parameters=None):
        """Static path: one joint functional update node for ALL parameters
        (regularizer → grad clip → per-param _apply_one, same pipeline as
        the eager step()); optimizer state slots become persistable
        variables.  The learning rate is read at evaluation time, so
        schedulers act per Executor.run; a CompiledProgram bakes the value
        current at first compile (reference CompiledProgram semantics)."""
        import jax.numpy as jnp

        from ..static.graph import Variable as _StaticVar
        from ..static.graph import (append_backward,
                                    default_startup_program, global_scope)

        pairs = append_backward(loss, parameter_list=parameters)
        scope = global_scope()
        prog = loss.program
        params = [p for p, _ in pairs]
        grads = [g for _, g in pairs]
        layout: list = []          # per-param sorted state keys
        state_vars: list = []      # flat persist vars matching layout
        for param in params:
            probe = type("_P", (), {"value": jnp.zeros(tuple(param.shape),
                                                       param.dtype),
                                    "name": param.name,
                                    "stop_gradient": False})()
            slots = {k: v for k, v in self._init_state(probe).items()
                     if hasattr(v, "shape")}
            keys = sorted(slots)
            layout.append(keys)
            for k in keys:
                sv = _StaticVar("persist", "%s__%s" % (param.name, k),
                                slots[k].shape, slots[k].dtype, prog,
                                meta={"trainable": False})
                init_val = slots[k]
                default_startup_program()._initializers.append(
                    (sv, (lambda v: (lambda: jnp.asarray(v)))(init_val)))
                scope._values.setdefault(sv.name, jnp.asarray(init_val))
                state_vars.append(sv)

        n = len(params)

        def apply_all(*vals):
            p_vals = list(vals[:n])
            g_vals = list(vals[n:2 * n])
            s_vals = list(vals[2 * n:])
            pg = [(p, self._regularized(p, pv, gv))
                  for p, pv, gv in zip(params, p_vals, g_vals)]
            if self._grad_clip is not None:
                pg = self._grad_clip(pg)
            lr = jnp.asarray(self._lr_value(), jnp.float32)
            outs = []
            si = 0
            for (p, g), pv, keys in zip(pg, p_vals, layout):
                state = dict(zip(keys, s_vals[si:si + len(keys)]))
                si += len(keys)
                new_val, new_state = self._apply_one(pv, g, state, lr, p)
                outs.append(new_val)
                outs.extend(new_state[k] for k in keys)
            return tuple(outs)

        bundle = _StaticVar(
            "op", None, params[0].shape, params[0].dtype, prog, op=apply_all,
            inputs=(tuple(params) + tuple(grads) + tuple(state_vars), {}),
            meta={"op_name": "optimizer_update"})

        def pick(i, shape, dtype):
            return _StaticVar(
                "op", None, shape, dtype, prog,
                op=(lambda t, _i=i: t[_i]), inputs=((bundle,), {}),
                meta={"op_name": "optimizer_update_slot"})

        out_i = 0
        sv_i = 0
        for param, keys in zip(params, layout):
            prog._updates.append((param, pick(out_i, param.shape,
                                              param.dtype)))
            out_i += 1
            for k in keys:
                sv = state_vars[sv_i]
                prog._updates.append((sv, pick(out_i, sv.shape, sv.dtype)))
                out_i += 1
                sv_i += 1
        return None, list(pairs)

    def _lr_value(self):
        lr = self._learning_rate
        return lr() if callable(lr) and not isinstance(lr, (int, float)) \
            else (lr.get_lr() if hasattr(lr, "get_lr") else float(lr))

    # -- checkpoint -------------------------------------------------------
    def state_dict(self) -> dict:
        sd: dict = {}
        for pname, state in self._states.items():
            for k, v in state.items():
                sd["%s__%s" % (pname, k)] = Tensor(v)
        if isinstance(self._learning_rate, LRScheduler):
            sd["LR_Scheduler"] = self._learning_rate.state_dict()
        return sd

    def set_state_dict(self, state_dict: dict) -> None:
        sched = state_dict.get("LR_Scheduler")
        if sched is not None and isinstance(self._learning_rate, LRScheduler):
            self._learning_rate.set_state_dict(dict(sched))
        grouped: dict = {}
        for key, v in state_dict.items():
            if key == "LR_Scheduler" or "__" not in key:
                continue
            pname, slot = key.rsplit("__", 1)
            val = v.value if isinstance(v, Tensor) else jnp.asarray(np.asarray(v))
            grouped.setdefault(pname, {})[slot] = val
        # Saved names may come from another process/construction epoch (the
        # auto name counter keeps counting), so fall back to positional
        # mapping onto this optimizer's trainable parameters when the name
        # sets differ — state_dict insertion order tracks parameter order.
        # Shape-validate every non-scalar slot against its target parameter
        # so a wrong mapping fails loudly instead of silently corrupting.
        mapping = {n: n for n in grouped}
        trainable = [p for p in (self._parameter_list or [])
                     if not p.stop_gradient]
        current = [p.name for p in trainable]
        if current and set(grouped) != set(current):
            if len(grouped) != len(current):
                raise InvalidArgumentError(
                    "optimizer state has %d parameter entries %r but this "
                    "optimizer tracks %d parameters %r"
                    % (len(grouped), sorted(grouped), len(current),
                       sorted(current)))
            # Positional fallback is only safe when the names differ by the
            # auto-name counter alone (same structural stems in the same
            # order) — shape checks cannot distinguish identically-shaped
            # parameters, so a looser match could silently swap moments.
            stem = lambda n: n.rstrip("0123456789")
            saved_names = list(grouped.keys())
            if [stem(n) for n in saved_names] != [stem(n) for n in current]:
                raise InvalidArgumentError(
                    "optimizer state parameter names %r do not positionally "
                    "match this optimizer's parameters %r (structural stems "
                    "differ) — refusing positional state mapping"
                    % (saved_names, current))
            for sname, tname in zip(saved_names, current):
                have = self._states.get(tname)
                if have and frozenset(have) != frozenset(grouped[sname]):
                    raise InvalidArgumentError(
                        "optimizer state entry %r carries slots %r but "
                        "target parameter %r already has slots %r — "
                        "refusing positional state mapping"
                        % (sname, sorted(grouped[sname]), tname,
                           sorted(have)))
            mapping = dict(zip(saved_names, current))
        by_name = {p.name: p for p in trainable}
        for pname, slots in grouped.items():
            tgt = mapping[pname]
            p = by_name.get(tgt)
            if p is not None:
                for slot, val in slots.items():
                    if getattr(val, "ndim", 0) > 0 \
                            and tuple(val.shape) != tuple(p.value.shape):
                        raise InvalidArgumentError(
                            "optimizer state %r slot %r has shape %s but "
                            "parameter %r has shape %s — state_dict does "
                            "not match this optimizer's parameters"
                            % (pname, slot, tuple(val.shape), tgt,
                               tuple(p.value.shape)))
            self._states.setdefault(tgt, {}).update(slots)

    set_dict = set_state_dict


class SGD(Optimizer):
    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None, grad_clip=None, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, multi_precision, name)

    def _apply_one(self, val, grad, state, lr, p):
        m = self._master(val, state)
        new = m - lr * grad.astype(m.dtype)
        return self._finish(new, val.dtype, state)

    def _supports_sparse(self, p, state) -> bool:
        # sgd_op's SelectedRows branch: plain row subtraction
        return ("master_weight" not in state
                and getattr(p, "regularizer", None) is None
                and self._weight_decay is None)

    def _apply_one_sparse(self, val, grad, state, lr, p):
        delta = (lr * grad.values.astype(val.dtype))
        return val.at[grad.indices].add(-delta), state


class Momentum(Optimizer):
    """operators/optimizers/momentum_op semantics incl. use_nesterov."""

    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, multi_precision, name)
        self._momentum = momentum
        self._use_nesterov = use_nesterov

    def _init_state(self, p):
        state = super()._init_state(p)
        m = state.get("master_weight", p.value)
        state["velocity"] = jnp.zeros_like(m)
        return state

    def _apply_one(self, val, grad, state, lr, p):
        m = self._master(val, state)
        g = grad.astype(m.dtype)
        v = self._momentum * state["velocity"] + g
        if self._use_nesterov:
            new = m - lr * (g + self._momentum * v)
        else:
            new = m - lr * v
        new_val, state = self._finish(new, val.dtype, state)
        state = dict(state, velocity=v)
        return new_val, state


class Adam(Optimizer):
    """operators/optimizers/adam_op.cc:234 semantics (bias-corrected, optional
    multi-precision master weights)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 parameters=None, weight_decay=None, grad_clip=None, lazy_mode=False,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, multi_precision, name)
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon
        self._lazy_mode = bool(lazy_mode)

    def _init_state(self, p):
        state = super()._init_state(p)
        m = state.get("master_weight", p.value)
        state["moment1"] = jnp.zeros_like(m, dtype=jnp.float32)
        state["moment2"] = jnp.zeros_like(m, dtype=jnp.float32)
        state["beta1_pow"] = jnp.asarray(1.0, jnp.float32)
        state["beta2_pow"] = jnp.asarray(1.0, jnp.float32)
        return state

    def _adam_update(self, m_w, grad, state, lr):
        g = grad.astype(jnp.float32)
        m1 = self._beta1 * state["moment1"] + (1 - self._beta1) * g
        m2 = self._beta2 * state["moment2"] + (1 - self._beta2) * jnp.square(g)
        b1p = state["beta1_pow"] * self._beta1
        b2p = state["beta2_pow"] * self._beta2
        mhat = m1 / (1 - b1p)
        vhat = m2 / (1 - b2p)
        delta = lr * mhat / (jnp.sqrt(vhat) + self._epsilon)
        new_state = dict(state, moment1=m1, moment2=m2, beta1_pow=b1p, beta2_pow=b2p)
        return delta.astype(m_w.dtype), new_state

    def _apply_one(self, val, grad, state, lr, p):
        m = self._master(val, state)
        delta, state = self._adam_update(m, grad, state, lr)
        new = m - delta
        new_val, state2 = self._finish(new, val.dtype, state)
        return new_val, state2

    def _supports_sparse(self, p, state) -> bool:
        # adam_op.cc lazy_mode: only rows present in the SelectedRows grad
        # get moment/param updates (beta pows still advance globally)
        return (self._lazy_mode and "master_weight" not in state
                and getattr(p, "regularizer", None) is None
                and self._weight_decay is None)

    def _apply_one_sparse(self, val, grad, state, lr, p):
        rows = grad.indices
        g = grad.values.astype(jnp.float32)
        m1r = self._beta1 * state["moment1"][rows] + (1 - self._beta1) * g
        m2r = self._beta2 * state["moment2"][rows] + \
            (1 - self._beta2) * jnp.square(g)
        b1p = state["beta1_pow"] * self._beta1
        b2p = state["beta2_pow"] * self._beta2
        mhat = m1r / (1 - b1p)
        vhat = m2r / (1 - b2p)
        delta = lr * mhat / (jnp.sqrt(vhat) + self._epsilon)
        new_val = val.at[rows].add(-delta.astype(val.dtype))
        new_state = dict(state,
                         moment1=state["moment1"].at[rows].set(m1r),
                         moment2=state["moment2"].at[rows].set(m2r),
                         beta1_pow=b1p, beta2_pow=b2p)
        return new_val, new_state


class AdamW(Adam):
    """Decoupled weight decay (python/paddle/optimizer/adamw.py)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 parameters=None, weight_decay=0.01, lr_ratio=None, apply_decay_param_fun=None,
                 grad_clip=None, lazy_mode=False, multi_precision=False, name=None):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         None, grad_clip, lazy_mode, multi_precision, name)
        self._coeff = weight_decay if isinstance(weight_decay, float) else getattr(weight_decay, "coeff", 0.01)
        self._apply_decay_param_fun = apply_decay_param_fun
        self._lr_ratio = lr_ratio

    @property
    def _decoupled_decay(self):
        return True

    def _apply_one(self, val, grad, state, lr, p):
        m = self._master(val, state)
        if self._lr_ratio is not None:
            lr = lr * self._lr_ratio(p)
        decay = self._coeff
        if self._apply_decay_param_fun is not None and not self._apply_decay_param_fun(p.name):
            decay = 0.0
        delta, state = self._adam_update(m, grad, state, lr)
        new = m * (1.0 - lr * decay) - delta
        return self._finish(new, val.dtype, state)

    def _supports_sparse(self, p, state) -> bool:
        # decoupled decay touches EVERY row each step — incompatible with
        # lazy row updates unless the decay is zero for this parameter
        decay = self._coeff
        if self._apply_decay_param_fun is not None and \
                not self._apply_decay_param_fun(p.name):
            decay = 0.0
        return decay == 0.0 and super()._supports_sparse(p, state)

    def _apply_one_sparse(self, val, grad, state, lr, p):
        if self._lr_ratio is not None:  # same lr scaling as the dense path
            lr = lr * self._lr_ratio(p)
        return super()._apply_one_sparse(val, grad, state, lr, p)


class Adagrad(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, parameters=None, weight_decay=None,
                 grad_clip=None, initial_accumulator_value=0.0, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, False, name)
        self._epsilon = epsilon
        self._init_acc = initial_accumulator_value

    def _init_state(self, p):
        state = super()._init_state(p)
        state["moment"] = jnp.full_like(p.value, self._init_acc, dtype=jnp.float32)
        return state

    def _apply_one(self, val, grad, state, lr, p):
        g = grad.astype(jnp.float32)
        acc = state["moment"] + jnp.square(g)
        new = val - (lr * g / (jnp.sqrt(acc) + self._epsilon)).astype(val.dtype)
        return new, dict(state, moment=acc)


class Adadelta(Optimizer):
    def __init__(self, learning_rate=0.001, epsilon=1e-6, rho=0.95, parameters=None,
                 weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, False, name)
        self._epsilon = epsilon
        self._rho = rho

    def _init_state(self, p):
        state = super()._init_state(p)
        state["avg_squared_grad"] = jnp.zeros_like(p.value, dtype=jnp.float32)
        state["avg_squared_update"] = jnp.zeros_like(p.value, dtype=jnp.float32)
        return state

    def _apply_one(self, val, grad, state, lr, p):
        g = grad.astype(jnp.float32)
        asg = self._rho * state["avg_squared_grad"] + (1 - self._rho) * jnp.square(g)
        update = -jnp.sqrt((state["avg_squared_update"] + self._epsilon) / (asg + self._epsilon)) * g
        asu = self._rho * state["avg_squared_update"] + (1 - self._rho) * jnp.square(update)
        new = val + (lr * update).astype(val.dtype)
        return new, dict(state, avg_squared_grad=asg, avg_squared_update=asu)


class Adamax(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 parameters=None, weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, False, name)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _init_state(self, p):
        state = super()._init_state(p)
        state["moment"] = jnp.zeros_like(p.value, dtype=jnp.float32)
        state["inf_norm"] = jnp.zeros_like(p.value, dtype=jnp.float32)
        state["beta1_pow"] = jnp.asarray(1.0, jnp.float32)
        return state

    def _apply_one(self, val, grad, state, lr, p):
        g = grad.astype(jnp.float32)
        m = self._beta1 * state["moment"] + (1 - self._beta1) * g
        u = jnp.maximum(self._beta2 * state["inf_norm"], jnp.abs(g) + self._epsilon)
        b1p = state["beta1_pow"] * self._beta1
        new = val - (lr / (1 - b1p) * m / u).astype(val.dtype)
        return new, dict(state, moment=m, inf_norm=u, beta1_pow=b1p)


class RMSProp(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0, centered=False,
                 parameters=None, weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, False, name)
        self._rho, self._epsilon, self._momentum, self._centered = rho, epsilon, momentum, centered

    def _init_state(self, p):
        state = super()._init_state(p)
        state["mean_square"] = jnp.zeros_like(p.value, dtype=jnp.float32)
        state["momentum"] = jnp.zeros_like(p.value, dtype=jnp.float32)
        if self._centered:
            state["mean_grad"] = jnp.zeros_like(p.value, dtype=jnp.float32)
        return state

    def _apply_one(self, val, grad, state, lr, p):
        g = grad.astype(jnp.float32)
        ms = self._rho * state["mean_square"] + (1 - self._rho) * jnp.square(g)
        if self._centered:
            mg = self._rho * state["mean_grad"] + (1 - self._rho) * g
            denom = jnp.sqrt(ms - jnp.square(mg) + self._epsilon)
            state = dict(state, mean_grad=mg)
        else:
            denom = jnp.sqrt(ms + self._epsilon)
        mom = self._momentum * state["momentum"] + lr * g / denom
        new = val - mom.astype(val.dtype)
        return new, dict(state, mean_square=ms, momentum=mom)


class Lamb(Optimizer):
    """operators/optimizers/lamb_op semantics (layer-adaptive large batch)."""

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01, beta1=0.9, beta2=0.999,
                 epsilon=1e-6, parameters=None, grad_clip=None, exclude_from_weight_decay_fn=None,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip, multi_precision, name)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon
        self._lamb_decay = lamb_weight_decay
        self._exclude_fn = exclude_from_weight_decay_fn

    def _init_state(self, p):
        state = super()._init_state(p)
        m = state.get("master_weight", p.value)
        state["moment1"] = jnp.zeros_like(m, dtype=jnp.float32)
        state["moment2"] = jnp.zeros_like(m, dtype=jnp.float32)
        state["beta1_pow"] = jnp.asarray(1.0, jnp.float32)
        state["beta2_pow"] = jnp.asarray(1.0, jnp.float32)
        return state

    def _apply_one(self, val, grad, state, lr, p):
        m_w = self._master(val, state).astype(jnp.float32)
        g = grad.astype(jnp.float32)
        m1 = self._beta1 * state["moment1"] + (1 - self._beta1) * g
        m2 = self._beta2 * state["moment2"] + (1 - self._beta2) * jnp.square(g)
        b1p = state["beta1_pow"] * self._beta1
        b2p = state["beta2_pow"] * self._beta2
        mhat = m1 / (1 - b1p)
        vhat = m2 / (1 - b2p)
        decay = self._lamb_decay
        if self._exclude_fn is not None and self._exclude_fn(p):
            decay = 0.0
        r = mhat / (jnp.sqrt(vhat) + self._epsilon) + decay * m_w
        w_norm = jnp.sqrt(jnp.sum(jnp.square(m_w)))
        r_norm = jnp.sqrt(jnp.sum(jnp.square(r)))
        trust = jnp.where((w_norm > 0) & (r_norm > 0), w_norm / r_norm, 1.0)
        new = m_w - lr * trust * r
        new_val, state2 = self._finish(new, val.dtype, dict(state, moment1=m1, moment2=m2, beta1_pow=b1p, beta2_pow=b2p))
        return new_val, state2


class Lars(Optimizer):
    """operators/optimizers/lars_momentum_op semantics."""

    def __init__(self, learning_rate=0.001, momentum=0.9, lars_coeff=0.001,
                 lars_weight_decay=0.0005, parameters=None, grad_clip=None,
                 exclude_from_weight_decay=None, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip, multi_precision, name)
        self._momentum = momentum
        self._lars_coeff = lars_coeff
        self._lars_decay = lars_weight_decay
        self._exclude = exclude_from_weight_decay or []

    def _init_state(self, p):
        state = super()._init_state(p)
        m = state.get("master_weight", p.value)
        state["velocity"] = jnp.zeros_like(m, dtype=jnp.float32)
        return state

    def _apply_one(self, val, grad, state, lr, p):
        m_w = self._master(val, state).astype(jnp.float32)
        g = grad.astype(jnp.float32)
        decay = self._lars_decay
        if any(tag in (p.name or "") for tag in self._exclude):
            decay = 0.0
        w_norm = jnp.sqrt(jnp.sum(jnp.square(m_w)))
        g_norm = jnp.sqrt(jnp.sum(jnp.square(g)))
        local_lr = jnp.where(
            (w_norm > 0) & (g_norm > 0),
            self._lars_coeff * w_norm / (g_norm + decay * w_norm + 1e-12),
            1.0,
        )
        v = self._momentum * state["velocity"] + lr * local_lr * (g + decay * m_w)
        new = m_w - v
        new_val, state2 = self._finish(new, val.dtype, dict(state, velocity=v))
        return new_val, state2


class Ftrl(Optimizer):
    """operators/optimizers/ftrl_op semantics (FTRL-proximal).

    squared/linear accumulators; the closed-form proximal update
    ``w = -linear_clipped / (l2 + sqrt(new_sq)/lr)`` with l1 soft threshold.
    """

    def __init__(self, learning_rate=0.001, l1=0.0, l2=0.0, lr_power=-0.5,
                 parameters=None, weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, False, name)
        self._l1 = l1
        self._l2 = l2
        self._lr_power = lr_power

    def _init_state(self, p):
        state = super()._init_state(p)
        state["squared"] = jnp.zeros_like(p.value, dtype=jnp.float32)
        state["linear"] = jnp.zeros_like(p.value, dtype=jnp.float32)
        return state

    def _apply_one(self, val, grad, state, lr, p):
        g = grad.astype(jnp.float32)
        w = val.astype(jnp.float32)
        sq, lin = state["squared"], state["linear"]
        new_sq = sq + jnp.square(g)
        pw = -self._lr_power
        sigma = (jnp.power(new_sq, pw) - jnp.power(sq, pw)) / lr
        new_lin = lin + g - sigma * w
        quad = jnp.power(new_sq, pw) / lr + 2.0 * self._l2
        pre = jnp.clip(new_lin, -self._l1, self._l1) - new_lin
        new = jnp.where(jnp.abs(new_lin) > self._l1, pre / quad, 0.0)
        return new.astype(val.dtype), dict(state, squared=new_sq, linear=new_lin)


class Lookahead:
    """fluid/optimizer.py:5969 LookaheadOptimizer semantics: an inner (fast)
    optimizer steps normally; every ``k`` steps the slow weights move
    ``alpha`` of the way toward the fast weights and the fast weights are
    reset onto them.  Non-subclassing wrapper (the meta_optimizers pattern):
    unknown attributes delegate to the inner optimizer, so the jit TrainStep
    machinery (_parameter_list/_states/_functional_step) sees the inner
    optimizer's state directly."""

    def __init__(self, inner_optimizer, alpha: float = 0.5, k: int = 5):
        if inner_optimizer is None:
            raise InvalidArgumentError("Lookahead needs an inner optimizer")
        if not 0.0 <= alpha <= 1.0:
            raise InvalidArgumentError("alpha must be in [0, 1]")
        if k < 1:
            raise InvalidArgumentError("k must be a positive integer")
        self._inner = inner_optimizer
        self.alpha = alpha
        self.k = k
        self._step_count = 0
        # keyed by position in the inner parameter list: auto-generated
        # param names differ across processes, positions do not
        self._slow: dict = {}
        # reference LookaheadOptimizer snapshots the slow weights at
        # minimize start; capture now so the first sync interpolates from
        # the *initial* weights, not the already-advanced fast weights
        self._seed_slow()

    def _seed_slow(self) -> None:
        for i, p in enumerate(self._inner._parameter_list or ()):
            if p.stop_gradient or i in self._slow:
                continue
            self._slow[i] = jnp.array(p.value, copy=True)

    @property
    def inner_opt(self):
        return self._inner

    @property
    def _parameter_list(self):
        return self._inner._parameter_list

    @_parameter_list.setter
    def _parameter_list(self, params):
        # writes must reach the inner optimizer (TrainStep assigns this
        # when the optimizer was built without parameters=)
        self._inner._parameter_list = params
        self._seed_slow()

    def __getattr__(self, name):
        if name == "_inner":  # guard: deepcopy/pickle probe pre-__init__
            raise AttributeError(name)
        return getattr(self._inner, name)

    def _functional_step(self, *args, **kwargs):
        raise NotImplementedError(
            "Lookahead's k-step slow-weight sync is host-side state and "
            "does not compose with the jitted TrainStep; jit the inner "
            "optimizer (TrainStep(model, loss_fn, opt.inner_opt)) and call "
            "opt.sync() every k steps, or train eagerly via "
            "backward()/opt.step()")

    def sync(self) -> None:
        """Force a slow-weight sync now (for jitted training loops that
        step the inner optimizer directly)."""
        self._step_count = 0
        for i, p in enumerate(self._inner._parameter_list or ()):
            if p.stop_gradient:
                continue
            slow = self._slow.get(i)
            if slow is None:
                # parameters attached to the inner optimizer after __init__
                # (e.g. TrainStep assigns inner._parameter_list directly):
                # the initial snapshot is unrecoverable here, so this first
                # sync is a no-op for this param. Warn — constructing the
                # inner optimizer with parameters= gives reference-faithful
                # first-sync behavior.
                import warnings

                warnings.warn(
                    "Lookahead slow weights were never seeded for param %d "
                    "(parameters attached after construction); first sync "
                    "is a no-op for it. Pass parameters= to the inner "
                    "optimizer before wrapping to match the reference's "
                    "minimize-start snapshot." % i)
                slow = p.value
            slow = slow + self.alpha * (p.value - slow)
            # independent copy: the param's buffer may be donated by a
            # jitted TrainStep, which would delete a shared reference
            self._slow[i] = jnp.array(slow, copy=True)
            p.set_value(slow)

    def step(self) -> None:
        self._seed_slow()  # params attached after __init__: snapshot pre-step
        self._inner.step()
        self._step_count += 1
        if self._step_count % self.k:
            return
        for i, p in enumerate(self._inner._parameter_list or ()):
            if p.stop_gradient:
                continue
            slow = self._slow.get(i)
            if slow is None:
                slow = p.value
            slow = slow + self.alpha * (p.value - slow)
            # independent copy: the param's buffer may be donated by a
            # jitted TrainStep, which would delete a shared reference
            self._slow[i] = jnp.array(slow, copy=True)
            p.set_value(slow)

    def clear_grad(self, *args, **kwargs) -> None:
        self._inner.clear_grad(*args, **kwargs)

    def state_dict(self) -> dict:
        sd = self._inner.state_dict()
        sd["__lookahead_step__"] = Tensor(jnp.asarray(self._step_count))
        for i, slow in self._slow.items():
            sd["__lookahead_slow__%d" % i] = Tensor(slow)
        return sd

    def set_state_dict(self, state_dict: dict) -> None:
        state_dict = dict(state_dict)
        step = state_dict.pop("__lookahead_step__", None)
        if step is not None:
            self._step_count = int(np.asarray(
                step.value if hasattr(step, "value") else step))
        self._slow = {}
        for key in [k for k in state_dict if
                    k.startswith("__lookahead_slow__")]:
            v = state_dict.pop(key)
            self._slow[int(key[len("__lookahead_slow__"):])] = jnp.asarray(
                v.value if hasattr(v, "value") else v)
        if state_dict:  # stateless inner optimizers (SGD) save no slots
            self._inner.set_state_dict(state_dict)

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        from ..static.graph import Variable as _StaticVar

        if isinstance(loss, _StaticVar):
            raise NotImplementedError(
                "Lookahead is an eager-mode wrapper on this stack; for "
                "static programs minimize with the inner optimizer")
        if loss._node is not None:
            loss.backward()
        self.step()
        return None, None


class ModelAverage:
    """fluid/optimizer.py:3573 ModelAverage semantics (dygraph form):
    maintain a running average of parameter values; ``apply()`` swaps the
    averaged weights in for evaluation, ``restore()`` swaps back.  The
    effective window follows the reference:
    ``min(max(num_updates * rate, min_window), max_window)``."""

    def __init__(self, average_window_rate: float = 0.15,
                 parameters: Optional[Sequence] = None,
                 min_average_window: int = 10000,
                 max_average_window: int = 10000, name=None):
        if parameters is None:
            raise InvalidArgumentError(
                "ModelAverage needs parameters=model.parameters()")
        self._params = [p for p in parameters if not p.stop_gradient]
        self._rate = average_window_rate
        self._min_w = min_average_window
        self._max_w = max_average_window
        self._sums = {p.name: jnp.zeros_like(p.value) for p in self._params}
        self._count = 0.0
        self._updates = 0
        self._saved: Optional[dict] = None

    def step(self) -> None:
        """Accumulate the current weights (call after optimizer.step())."""
        self._updates += 1
        window = min(max(self._updates * self._rate, self._min_w),
                     self._max_w)
        decay = 1.0 if self._count < window else float(window) / (window + 1)
        for p in self._params:
            self._sums[p.name] = self._sums[p.name] * decay + p.value
        self._count = self._count * decay + 1 if self._count >= window \
            else self._count + 1

    @contextlib.contextmanager
    def apply(self, executor=None, need_restore: bool = True):
        if self._count == 0:
            raise InvalidArgumentError(
                "ModelAverage.apply before any accumulation step()")
        self._saved = {p.name: p.value for p in self._params}
        for p in self._params:
            p.set_value(self._sums[p.name] / self._count)
        try:
            yield
        finally:
            if need_restore:
                self.restore()

    def restore(self, executor=None) -> None:
        if self._saved is None:
            return
        for p in self._params:
            p.set_value(self._saved[p.name])
        self._saved = None
