"""Flash attention: tiled online-softmax attention for TPU.

Reference parity: ``paddle.incubate.nn.functional.fused_multi_head_attention``
/ ``operators/fused/fused_attention_op.cu`` (one fused kernel instead of
matmul→softmax→matmul round-tripping scores through HBM).

TPU-native design: the pallas flash-attention kernel
(``jax.experimental.pallas.ops.tpu.flash_attention``) streams K/V blocks
through VMEM with an online softmax, so HBM traffic is O(L·D) instead of
O(L²) — the canonical MXU/VMEM blocking from the pallas guide.  Forward and
backward are both pallas kernels (custom_vjp built in).  ``flash_attention``
here adds the shape/backend gate and an XLA-composition fallback so the same
call works on CPU test meshes and odd shapes.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["flash_attention", "flash_attention_supported",
           "decode_attention", "decode_attention_supported",
           "paged_decode_attention", "paged_decode_attention_supported",
           "quantize_kv", "dequantize_kv"]

_SUPPORTED_DTYPES = (jnp.float32, jnp.bfloat16)

# Measured crossover on v5e (bf16, head_dim 64, fwd+bwd, tokens held
# constant): XLA's fused composition wins below ~4k sequence (5.2ms vs 6.7ms
# at L=512·B=16; 9.2 vs 12.1 at L=2048·B=4), the pallas kernel wins above
# (22.2 vs 19.4 at L=4096·B=2) where the O(L²) HBM scores dominate.
FLASH_MIN_SEQ = 4096


def flash_attention_supported(q_shape, dtype, dropout_p: float = 0.0) -> bool:
    """Gate: pallas kernel needs TPU, 4-D [B,H,L,D], MXU-tileable L and D,
    no attention-weight dropout (the kernel never materializes weights),
    and a sequence long enough that tiling beats XLA's fused composition."""
    if jax.default_backend() != "tpu":
        return False
    if dropout_p > 0.0:
        return False
    if len(q_shape) != 4:
        return False
    b, h, l, d = q_shape
    if l % 128 != 0 or l < FLASH_MIN_SEQ:
        return False
    if d not in (64, 128, 256):
        return False
    return jnp.dtype(dtype) in _SUPPORTED_DTYPES


def _reference_attention(q, k, v, bias, causal, sm_scale, segment_ids=None):
    scores = jnp.einsum("...qd,...kd->...qk", q, k) * jnp.asarray(
        sm_scale, q.dtype)
    if causal:
        ql, kl = scores.shape[-2], scores.shape[-1]
        allow = jnp.tril(jnp.ones((ql, kl), dtype=bool))
        scores = jnp.where(allow, scores, jnp.finfo(scores.dtype).min)
    if segment_ids is not None:
        q_seg, kv_seg = segment_ids
        same = q_seg[:, None, :, None] == kv_seg[:, None, None, :]
        scores = jnp.where(same, scores, jnp.finfo(scores.dtype).min)
    if bias is not None:
        scores = scores + bias.astype(scores.dtype)
    weights = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("...qk,...kd->...qd", weights, v)


def flash_attention(q, k, v, bias=None, causal: bool = False,
                    sm_scale: Optional[float] = None,
                    key_padding_mask=None, segment_ids=None):
    """[B, H, L, D] attention; pallas kernel on TPU, XLA fallback elsewhere.

    ``bias``: additive attention bias broadcastable to [B, H, Lq, Lk]
    (the paddle additive attn_mask convention).  Prefer the O(L) forms for
    ragged batches — they never materialize an [L, L] mask:

    ``key_padding_mask``: [B, Lk] bool, True = real token (from
    ``tensor.sequence_mask``); padded keys are excluded from every softmax.
    ``segment_ids``: ([B, Lq], [B, Lk]) int pair — attention is confined to
    positions with equal ids (packed-sequence / LoD batches, from
    ``tensor.lengths_to_segment_ids``); maps directly onto the pallas
    kernel's SegmentIds lanes.
    """
    d = q.shape[-1]
    if sm_scale is None:
        sm_scale = 1.0 / float(np.sqrt(d))
    if key_padding_mask is not None:
        if segment_ids is not None:
            raise ValueError(
                "pass either key_padding_mask or segment_ids, not both")
        # valid keys → segment 0; pads → 1.  Queries are all segment 0 (their
        # pad rows are ignored downstream), so every softmax sees only real
        # keys.  [B, L] ints instead of an [L, L] mask.
        kv_seg = jnp.where(jnp.asarray(key_padding_mask, bool), 0, 1) \
            .astype(jnp.int32)
        q_seg = jnp.zeros((q.shape[0], q.shape[2]), jnp.int32)
        segment_ids = (q_seg, kv_seg)
    elif segment_ids is not None:
        segment_ids = (jnp.asarray(segment_ids[0], jnp.int32),
                       jnp.asarray(segment_ids[1], jnp.int32))
    if not flash_attention_supported(q.shape, q.dtype):
        return _reference_attention(q, k, v, bias, causal, sm_scale,
                                    segment_ids)
    from jax.experimental.pallas.ops.tpu.flash_attention import (
        BlockSizes,
        SegmentIds,
        flash_attention as _pallas_flash,
    )

    from ..core.flags import flag as _flag

    ab = None
    if bias is not None:
        b_, h_, lq, lk = q.shape[0], q.shape[1], q.shape[2], k.shape[2]
        ab = jnp.broadcast_to(bias.astype(q.dtype), (b_, h_, lq, lk))
    # FLAGS_seq_block_size bounds the kernel's sequence tiles (VMEM budget
    # knob for very long sequences); 0/default lets the kernel choose.
    blk = int(_flag("FLAGS_seq_block_size") or 0)
    block_sizes = None
    lq, lk = q.shape[2], k.shape[2]
    if blk and (blk < min(lq, lk)) and lq % blk == 0 and lk % blk == 0:
        block_sizes = BlockSizes(
            block_q=blk, block_k_major=blk, block_k=blk, block_b=1,
            block_q_major_dkv=blk, block_k_major_dkv=blk, block_k_dkv=blk,
            block_q_dkv=blk, block_k_major_dq=blk, block_k_dq=blk,
            block_q_dq=blk)
    return _pallas_flash(q, k, v, ab=ab,
                         segment_ids=(SegmentIds(*segment_ids)
                                      if segment_ids is not None else None),
                         causal=causal,
                         sm_scale=float(sm_scale), block_sizes=block_sizes)


# ---------------------------------------------------------------------------
# int8 KV-cache quantization: per-head absmax scales
# ---------------------------------------------------------------------------

# Floor for the absmax scale: an all-zero head row (a never-written cache
# position) quantizes to zeros with a zero-ish scale instead of dividing
# by zero; any real activation dwarfs this.
KV_QUANT_EPS = 1e-8


def quantize_kv(x):
    """``[..., D]`` float K/V -> ``(int8 values [..., D], fp32 scales
    [...])`` — symmetric per-head absmax quantization, the granularity of
    the int8 KV cache: the quantization group is ONE head's ``[D]``
    vector at one position, so the scale tensor is the K/V buffer minus
    its head_dim axis (dense cache ``[B, H, S]``, paged pool
    ``[num_blocks, H, block_size]``).  Runs INSIDE the compiled
    prefill/decode step (quantize-on-write), the compiler-first
    discipline: cache dtype is a property of the program, not a host-side
    conversion pass."""
    xf = jnp.asarray(x, jnp.float32)
    scale = jnp.maximum(jnp.max(jnp.abs(xf), axis=-1), KV_QUANT_EPS) / 127.0
    q = jnp.clip(jnp.round(xf / scale[..., None]), -127, 127) \
        .astype(jnp.int8)
    return q, scale


def dequantize_kv(q, scale, dtype=jnp.float32):
    """Inverse of :func:`quantize_kv`: ``int8 [..., D]`` times its
    per-head ``[...]`` scales.  In the int8 decode paths this runs on
    the GATHERED rows inside the attention composition, so the HBM-side
    read of the cache is int8 and the fp up-cast happens in the fused
    kernel's registers/VMEM — the bandwidth side is where the win lives
    (EQuARX; decode is cache-bandwidth-bound)."""
    return q.astype(dtype) * scale[..., None].astype(dtype)


# ---------------------------------------------------------------------------
# decode-time attention: one (or few) query positions against a
# preallocated KV cache
# ---------------------------------------------------------------------------

# Same measured-crossover discipline as FLASH_MIN_SEQ: a kernel only
# replaces the XLA composition where a measurement says it wins.  The
# pallas flash kernel is shape-gated to Lq % 128 == 0, so a single-query
# decode step can NEVER take it; the decode-step composition below is a
# batched GEMV + softmax + GEMV that XLA fuses into one HBM pass over the
# cache, and no shipped kernel has beaten that below this cache length.
# When a paged/splash single-query kernel lands, its measured crossover
# replaces this constant the same way FLASH_MIN_SEQ was established.
DECODE_FLASH_MIN_CACHE = 16384


def decode_attention_supported(q_shape, kv_len: int, dtype) -> bool:
    """Gate for a future single-query pallas decode kernel: TPU backend,
    4-D [B, H, Lq, D] with a short query chunk, MXU-tileable head_dim and
    a cache long enough to beat the fused XLA composition.  Currently no
    such kernel ships, so the gate's callers always take the composition
    path below the crossover — the gate exists so the routing discipline
    (and its tests) are already in place when one lands."""
    if jax.default_backend() != "tpu":
        return False
    if len(q_shape) != 4 or q_shape[2] > 8:
        return False
    if q_shape[3] not in (64, 128, 256):
        return False
    if kv_len < DECODE_FLASH_MIN_CACHE:
        return False
    return jnp.dtype(dtype) in _SUPPORTED_DTYPES


def decode_attention(q, k, v, bias=None, sm_scale: Optional[float] = None,
                     k_scale=None, v_scale=None):
    """Decode-step attention: [B, H, Lq, D] queries against a FULL
    preallocated cache [B, H, S, D] (S = max_len), with ``bias`` masking
    the invalid tail (positions at or beyond the cache index) to -inf.

    Lq is the current chunk: 1 for autoregressive decode, spec_k+1 for
    a speculative VERIFY step (jit/speculative.py) — the verify chunk
    reuses this composition unchanged, which is why speculative logits
    equal plain decode logits up to reduction order, and why the
    single-query kernel gate below admits short chunks (Lq <= 8), not
    just Lq == 1.  The math is deliberately identical to the XLA
    fallback in
    ``F.scaled_dot_product_attention`` so cached and uncached logits
    agree to float-reduction noise.  Masked (garbage) cache positions
    contribute exp(-inf) == 0 to the softmax, so preallocation never
    changes the result, only the reduction shape — which XLA keeps
    shape-static across every decode step.

    ``k_scale``/``v_scale`` ([B, H, S] fp32) mark an int8-quantized
    cache: K/V arrive as int8 and are dequantized per head IN the
    composition (the HBM read is int8; the up-cast fuses into the score
    matmul).  The sm_scale default keys off the QUERY's head_dim, so the
    int8 path scores identically to fp32 up to quantization error."""
    d = q.shape[-1]
    if sm_scale is None:
        sm_scale = 1.0 / float(np.sqrt(d))
    if k_scale is not None:
        k = dequantize_kv(k, k_scale, q.dtype)
    if v_scale is not None:
        v = dequantize_kv(v, v_scale, q.dtype)
    if decode_attention_supported(q.shape, k.shape[2], q.dtype):
        # reserved routing slot: a paged/splash single-query kernel lands
        # here once a measured crossover justifies it; until then even a
        # gate-passing shape falls through to the fused composition
        pass
    scores = jnp.einsum("...qd,...kd->...qk", q, k) * jnp.asarray(
        sm_scale, q.dtype)
    if bias is not None:
        scores = scores + bias.astype(scores.dtype)
    weights = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("...qk,...kd->...qd", weights, v)


# ---------------------------------------------------------------------------
# paged decode attention: block-table KV cache (vLLM scheme, static shapes)
# ---------------------------------------------------------------------------


def paged_decode_attention_supported(q_shape, block_size: int,
                                     num_blocks: int, dtype) -> bool:
    """Gate for a future single-query pallas PAGED decode kernel, mirroring
    ``decode_attention_supported``: TPU backend, short query chunk,
    MXU-tileable head_dim, sublane-aligned block_size, and a pool big
    enough that a hand-tiled gather kernel could beat the XLA
    gather+composition.  No such kernel ships yet — callers always fall
    through to the composition — but the routing discipline (and its
    tests) are in place for when one measures in."""
    if jax.default_backend() != "tpu":
        return False
    if len(q_shape) != 4 or q_shape[2] > 8:
        return False
    if q_shape[3] not in (64, 128, 256):
        return False
    if block_size < 8 or block_size % 8 != 0:
        return False
    if block_size * num_blocks < DECODE_FLASH_MIN_CACHE:
        return False
    return jnp.dtype(dtype) in _SUPPORTED_DTYPES


def paged_decode_attention(q, k_pool, v_pool, table, lengths=None, bias=None,
                           sm_scale: Optional[float] = None,
                           k_scale=None, v_scale=None):
    """Decode-step attention against a BLOCK-TABLE KV cache.

    ``q``: [B, H, Lq, D] queries (Lq = 1 for autoregressive decode,
    spec_k+1 for a speculative verify chunk — same reuse discipline as
    ``decode_attention``).
    ``k_pool``/``v_pool``: [num_blocks, H, block_size, D] global block
    pools shared by every row.  ``table``: [B, max_blocks] int32 — row
    b's logical block j lives in physical pool row ``table[b, j]``
    (physical block 0 is by convention a scratch/trash block that
    unmapped logical blocks point at).  ``lengths``: optional scalar or
    [B] int32 count of VALID tokens per row; positions at or beyond it
    are masked to -inf.  ``bias`` is an extra additive mask
    broadcastable to [B, H, Lq, S] with S = max_blocks * block_size
    (callers that already know their causal-prefix mask pass it here and
    skip ``lengths``).

    ``k_scale``/``v_scale`` ([num_blocks, H, block_size] fp32) mark an
    int8-quantized pool: the per-head scales RIDE WITH their blocks
    (gathered through the same table, so a remapped block carries its
    own scales) and dequantization happens on the gathered rows — the
    pool read stays int8.

    All shapes are static — only the TABLE VALUES vary per step — so one
    XLA compilation serves every allocation state, the same
    compiler-first caching discipline as the dense ``decode_attention``
    (which this reduces to after the gather: the math is shared so paged
    and dense logits agree to float-reduction noise).  The pool rows a
    step can READ are exactly the mapped blocks, so cache HBM scales
    with allocated tokens, not max_len × rows.
    """
    b, mb = table.shape
    nb, h, bs, d = k_pool.shape
    s = mb * bs
    # gather the row's blocks: [B, MB, H, bs, D] -> [B, H, MB*bs, D];
    # XLA lowers the fancy-index to one gather over the pool's leading
    # axis, the only data-dependent op in the step
    tbl = jnp.asarray(table, jnp.int32)
    k = k_pool[tbl].transpose(0, 2, 1, 3, 4).reshape(b, h, s, d)
    v = v_pool[tbl].transpose(0, 2, 1, 3, 4).reshape(b, h, s, d)
    ks = vs = None
    if k_scale is not None:
        ks = k_scale[tbl].transpose(0, 2, 1, 3).reshape(b, h, s)
    if v_scale is not None:
        vs = v_scale[tbl].transpose(0, 2, 1, 3).reshape(b, h, s)
    if lengths is not None:
        lengths = jnp.asarray(lengths, jnp.int32)
        if lengths.ndim == 0:
            allow = (jnp.arange(s) < lengths)[None, None, None, :]
        else:
            allow = (jnp.arange(s)[None, :]
                     < lengths[:, None])[:, None, None, :]
        neg = jnp.asarray(jnp.finfo(jnp.float32).min, q.dtype)
        len_bias = jnp.where(allow, 0.0, neg)
        bias = len_bias if bias is None else bias + len_bias
    if paged_decode_attention_supported(q.shape, bs, nb, q.dtype):
        # reserved routing slot: a pallas paged/splash kernel that tiles
        # the gather lands here once a measured crossover justifies it
        pass
    return decode_attention(q, k, v, bias=bias, sm_scale=sm_scale,
                            k_scale=ks, v_scale=vs)


# id(mask) → (weakref(mask), verdict); masks are immutable jax arrays built
# once per model / per trace, so identity caching removes the repeated
# device→host readback.  Weakrefs keep the cache from pinning [L, L] masks
# after their models are freed, and a dead ref also invalidates the entry if
# a new allocation recycles the id (id-only keys are unsound).
_detect_cache: dict = {}
_DETECT_CACHE_MAX = 64


_pad_detect_cache: dict = {}


def detect_padding_additive_mask(mask):
    """[B, 1, 1, Lk] additive padding mask → [B, Lk] bool validity, else
    None.  Catches the standard paddle convention (0 = keep, big-negative =
    pad) so the flash path can use O(L) segment lanes instead of
    broadcasting the bias to [B, H, Lq, Lk] — the exact O(L²·H) HBM
    materialization the kernel exists to avoid.  Only the [B, 1, 1, Lk]
    layout is claimed: a 2-D additive mask means [Lq, Lk] in paddle, which
    is per-query, not key padding.  Concrete masks only; traced masks go
    down the general bias path.  Verdicts are identity-cached like
    ``detect_causal_additive_mask`` — masks are typically built once per
    model, and the readback is a blocking device→host copy."""
    if mask is None or isinstance(mask, jax.core.Tracer):
        return None
    shape = getattr(mask, "shape", None)
    if shape is None or len(shape) != 4 or shape[1] != 1 or shape[2] != 1:
        return None
    import weakref

    key = id(mask)
    hit = _pad_detect_cache.get(key)
    if hit is not None and hit[0]() is mask:
        return hit[1]
    m = np.asarray(mask)[:, 0, 0, :]
    if m.dtype == np.bool_:
        valid = m
    else:
        neg = np.finfo(np.float32).min / 2
        ok = m == 0
        pad = m <= neg
        valid = None if not np.all(ok | pad) else ok  # else: general bias
    try:
        ref = weakref.ref(mask)
    except TypeError:  # pragma: no cover - non-weakrefable array type
        return valid
    if len(_pad_detect_cache) >= _DETECT_CACHE_MAX:
        dead = [k for k, v in _pad_detect_cache.items() if v[0]() is None]
        for k in dead:
            del _pad_detect_cache[k]
        if len(_pad_detect_cache) >= _DETECT_CACHE_MAX:
            _pad_detect_cache.clear()
    _pad_detect_cache[key] = (ref, valid)
    return valid


def detect_causal_additive_mask(mask, seq_len: Optional[int] = None) -> bool:
    """True when ``mask`` is a concrete 2-D additive causal mask (0 on/below
    the diagonal, strictly large-negative above) matching ``seq_len`` — lets
    the kernel's causal fast path replace a materialized mask without
    changing the paddle API.  This also covers jitted callers whose mask is
    built from static shapes (constant-folded to a concrete array inside the
    trace, e.g. TransformerLM._causal_mask); masks that are runtime inputs
    arrive as tracers and safely skip detection."""
    if mask is None or isinstance(mask, jax.core.Tracer):
        return False
    if getattr(mask, "ndim", 0) != 2 or mask.shape[-1] != mask.shape[-2]:
        return False
    l = mask.shape[0]
    if l < 2:  # 1x1 has an empty upper triangle: vacuously "causal"
        return False
    if seq_len is not None and l != seq_len:
        return False  # broadcast-shaped masks keep their loud-error path
    import weakref

    key = id(mask)
    hit = _detect_cache.get(key)
    if hit is not None and hit[0]() is mask:
        return hit[1]
    m = np.asarray(mask)
    allow = np.tril(np.ones((l, l), dtype=bool))  # one L*L bool, no indices
    lower_ok = np.all(np.where(allow, m, 0) == 0)
    upper_ok = np.all(np.where(allow, np.finfo(np.float32).min, m)
                      <= np.finfo(np.float32).min / 2)
    verdict = bool(lower_ok and upper_ok)
    try:
        ref = weakref.ref(mask)
    except TypeError:  # pragma: no cover - non-weakrefable array type
        return verdict
    if len(_detect_cache) >= _DETECT_CACHE_MAX:
        dead = [k for k, v in _detect_cache.items() if v[0]() is None]
        for k in dead:
            del _detect_cache[k]
        if len(_detect_cache) >= _DETECT_CACHE_MAX:
            _detect_cache.clear()
    _detect_cache[key] = (ref, verdict)
    return verdict
