"""Flash attention: tiled online-softmax attention for TPU.

Reference parity: ``paddle.incubate.nn.functional.fused_multi_head_attention``
/ ``operators/fused/fused_attention_op.cu`` (one fused kernel instead of
matmul→softmax→matmul round-tripping scores through HBM).

TPU-native design: the pallas flash-attention kernel
(``jax.experimental.pallas.ops.tpu.flash_attention``) streams K/V blocks
through VMEM with an online softmax, so HBM traffic is O(L·D) instead of
O(L²) — the canonical MXU/VMEM blocking from the pallas guide.  Forward and
backward are both pallas kernels (custom_vjp built in).  ``flash_attention``
here adds the shape/backend gate and an XLA-composition fallback so the same
call works on CPU test meshes and odd shapes.
"""
from __future__ import annotations

import contextlib
import threading
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core.errors import InvalidArgumentError

__all__ = ["flash_attention", "flash_attention_supported",
           "decode_attention", "decode_attention_supported",
           "paged_decode_attention", "paged_decode_attention_supported",
           "quantize_kv", "dequantize_kv",
           "decode_route", "normalize_decode_route", "DECODE_ROUTES",
           "reset_backend_memo"]

_SUPPORTED_DTYPES = (jnp.float32, jnp.bfloat16)

# Measured crossover on v5e (bf16, head_dim 64, fwd+bwd, tokens held
# constant): XLA's fused composition wins below ~4k sequence (5.2ms vs 6.7ms
# at L=512·B=16; 9.2 vs 12.1 at L=2048·B=4), the pallas kernel wins above
# (22.2 vs 19.4 at L=4096·B=2) where the O(L²) HBM scores dominate.
FLASH_MIN_SEQ = 4096


def flash_attention_supported(q_shape, dtype, dropout_p: float = 0.0) -> bool:
    """Gate: pallas kernel needs TPU, 4-D [B,H,L,D], MXU-tileable L and D,
    no attention-weight dropout (the kernel never materializes weights),
    and a sequence long enough that tiling beats XLA's fused composition."""
    if jax.default_backend() != "tpu":
        return False
    if dropout_p > 0.0:
        return False
    if len(q_shape) != 4:
        return False
    b, h, l, d = q_shape
    if l % 128 != 0 or l < FLASH_MIN_SEQ:
        return False
    if d not in (64, 128, 256):
        return False
    return jnp.dtype(dtype) in _SUPPORTED_DTYPES


def _reference_attention(q, k, v, bias, causal, sm_scale, segment_ids=None):
    scores = jnp.einsum("...qd,...kd->...qk", q, k) * jnp.asarray(
        sm_scale, q.dtype)
    if causal:
        ql, kl = scores.shape[-2], scores.shape[-1]
        allow = jnp.tril(jnp.ones((ql, kl), dtype=bool))
        scores = jnp.where(allow, scores, jnp.finfo(scores.dtype).min)
    if segment_ids is not None:
        q_seg, kv_seg = segment_ids
        same = q_seg[:, None, :, None] == kv_seg[:, None, None, :]
        scores = jnp.where(same, scores, jnp.finfo(scores.dtype).min)
    if bias is not None:
        scores = scores + bias.astype(scores.dtype)
    weights = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("...qk,...kd->...qd", weights, v)


def flash_attention(q, k, v, bias=None, causal: bool = False,
                    sm_scale: Optional[float] = None,
                    key_padding_mask=None, segment_ids=None):
    """[B, H, L, D] attention; pallas kernel on TPU, XLA fallback elsewhere.

    ``bias``: additive attention bias broadcastable to [B, H, Lq, Lk]
    (the paddle additive attn_mask convention).  Prefer the O(L) forms for
    ragged batches — they never materialize an [L, L] mask:

    ``key_padding_mask``: [B, Lk] bool, True = real token (from
    ``tensor.sequence_mask``); padded keys are excluded from every softmax.
    ``segment_ids``: ([B, Lq], [B, Lk]) int pair — attention is confined to
    positions with equal ids (packed-sequence / LoD batches, from
    ``tensor.lengths_to_segment_ids``); maps directly onto the pallas
    kernel's SegmentIds lanes.
    """
    d = q.shape[-1]
    if sm_scale is None:
        sm_scale = 1.0 / float(np.sqrt(d))
    if key_padding_mask is not None:
        if segment_ids is not None:
            raise ValueError(
                "pass either key_padding_mask or segment_ids, not both")
        # valid keys → segment 0; pads → 1.  Queries are all segment 0 (their
        # pad rows are ignored downstream), so every softmax sees only real
        # keys.  [B, L] ints instead of an [L, L] mask.
        kv_seg = jnp.where(jnp.asarray(key_padding_mask, bool), 0, 1) \
            .astype(jnp.int32)
        q_seg = jnp.zeros((q.shape[0], q.shape[2]), jnp.int32)
        segment_ids = (q_seg, kv_seg)
    elif segment_ids is not None:
        segment_ids = (jnp.asarray(segment_ids[0], jnp.int32),
                       jnp.asarray(segment_ids[1], jnp.int32))
    if not flash_attention_supported(q.shape, q.dtype):
        return _reference_attention(q, k, v, bias, causal, sm_scale,
                                    segment_ids)
    from jax.experimental.pallas.ops.tpu.flash_attention import (
        BlockSizes,
        SegmentIds,
        flash_attention as _pallas_flash,
    )

    from ..core.flags import flag as _flag

    ab = None
    if bias is not None:
        b_, h_, lq, lk = q.shape[0], q.shape[1], q.shape[2], k.shape[2]
        ab = jnp.broadcast_to(bias.astype(q.dtype), (b_, h_, lq, lk))
    # FLAGS_seq_block_size bounds the kernel's sequence tiles (VMEM budget
    # knob for very long sequences); 0/default lets the kernel choose.
    blk = int(_flag("FLAGS_seq_block_size") or 0)
    block_sizes = None
    lq, lk = q.shape[2], k.shape[2]
    if blk and (blk < min(lq, lk)) and lq % blk == 0 and lk % blk == 0:
        block_sizes = BlockSizes(
            block_q=blk, block_k_major=blk, block_k=blk, block_b=1,
            block_q_major_dkv=blk, block_k_major_dkv=blk, block_k_dkv=blk,
            block_q_dkv=blk, block_k_major_dq=blk, block_k_dq=blk,
            block_q_dq=blk)
    return _pallas_flash(q, k, v, ab=ab,
                         segment_ids=(SegmentIds(*segment_ids)
                                      if segment_ids is not None else None),
                         causal=causal,
                         sm_scale=float(sm_scale), block_sizes=block_sizes)


# ---------------------------------------------------------------------------
# int8 KV-cache quantization: per-head absmax scales
# ---------------------------------------------------------------------------

# Floor for the absmax scale: an all-zero head row (a never-written cache
# position) quantizes to zeros with a zero-ish scale instead of dividing
# by zero; any real activation dwarfs this.
KV_QUANT_EPS = 1e-8


def quantize_kv(x):
    """``[..., D]`` float K/V -> ``(int8 values [..., D], fp32 scales
    [...])`` — symmetric per-head absmax quantization, the granularity of
    the int8 KV cache: the quantization group is ONE head's ``[D]``
    vector at one position, so the scale tensor is the K/V buffer minus
    its head_dim axis (dense cache ``[B, H, S]``, paged pool
    ``[num_blocks, H, block_size]``).  Runs INSIDE the compiled
    prefill/decode step (quantize-on-write), the compiler-first
    discipline: cache dtype is a property of the program, not a host-side
    conversion pass."""
    xf = jnp.asarray(x, jnp.float32)
    scale = jnp.maximum(jnp.max(jnp.abs(xf), axis=-1), KV_QUANT_EPS) / 127.0
    q = jnp.clip(jnp.round(xf / scale[..., None]), -127, 127) \
        .astype(jnp.int8)
    return q, scale


def dequantize_kv(q, scale, dtype=jnp.float32):
    """Inverse of :func:`quantize_kv`: ``int8 [..., D]`` times its
    per-head ``[...]`` scales.  In the int8 decode paths this runs on
    the GATHERED rows inside the attention composition, so the HBM-side
    read of the cache is int8 and the fp up-cast happens in the fused
    kernel's registers/VMEM — the bandwidth side is where the win lives
    (EQuARX; decode is cache-bandwidth-bound)."""
    return q.astype(dtype) * scale[..., None].astype(dtype)


# ---------------------------------------------------------------------------
# decode-time attention: one (or few) query positions against a
# preallocated KV cache
# ---------------------------------------------------------------------------

# Same measured-crossover discipline as FLASH_MIN_SEQ: a kernel only
# replaces the XLA composition where a measurement says it wins.  The
# pallas flash kernel is shape-gated to Lq % 128 == 0, so a single-query
# decode step can NEVER take it; the decode-step composition below is a
# batched GEMV + softmax + GEMV that XLA fuses into one HBM pass over the
# cache, and below this cache length no measurement has shown the fused
# pallas decode kernel (ops/pallas_decode.py) beating it.  Above it the
# "auto" route engages the kernel on TPU; ``tools/decode_sweep.py
# --route`` measures both paths so this constant is replaceable by a
# sweep, not a guess (the same way FLASH_MIN_SEQ was established).
DECODE_FLASH_MIN_CACHE = 16384

# -- decode routing ----------------------------------------------------
# "auto": the measured-crossover discipline — the fused pallas kernel
#   engages exactly where the ``*_supported`` gates say it wins (TPU
#   backend, short chunk, MXU-tileable head_dim, cache past the
#   crossover); everything else takes the XLA composition.
# "composition": force the gather+dequant+attention composition.
# "pallas": force the fused kernel wherever it structurally applies
#   (Lq <= 8, float queries) — off-TPU it runs under the pallas
#   INTERPRETER, which is how tier-1 tests pin numeric identity on CPU;
#   shapes the kernel cannot take (the bucketed prefill's long chunk)
#   silently keep the composition, so a forced session still prefills.
DECODE_ROUTES = ("auto", "composition", "pallas")

# The ambient route is THREAD-LOCAL (the repo's convention for ambient
# trace state — core/amp_state.py, core/random.py): the serving
# engine's loop thread traces its executables under its own route
# while the main thread may be warming another session, and a shared
# stack would let one thread pop the other's entry mid-trace.
_route_state = threading.local()


def _route_stack() -> list:
    stack = getattr(_route_state, "stack", None)
    if stack is None:
        stack = _route_state.stack = ["auto"]
    return stack


def normalize_decode_route(route) -> str:
    """Validated route name, or a typed error naming the choices —
    checked at session/pool construction AND at every explicit
    ``route=`` call site, so a typo'd route fails loudly instead of
    silently decoding on the wrong path."""
    if route not in DECODE_ROUTES:
        raise InvalidArgumentError(
            "decode route must be one of %s, got %r"
            % (list(DECODE_ROUTES), route))
    return route


@contextlib.contextmanager
def decode_route(route):
    """Ambient decode-attention routing for a trace region: the decode
    sessions wrap their model forwards in this so the ``route=`` knob
    reaches the attention ops buried under the layer stack without
    threading a kwarg through every ``forward``.  The route is
    PYTHON-static — it selects which ops get traced, so a session's
    executables are compiled for exactly one path and the compile-count
    contract is untouched."""
    stack = _route_stack()
    stack.append(normalize_decode_route(route))
    try:
        yield
    finally:
        stack.pop()


# jax.default_backend() walks the backend registry on every call; the
# decode gates run on EVERY trace of every decode-family executable, so
# the lookup is memoized at module level (the backend cannot change
# within a process once jax initializes).  ``reset_backend_memo`` is
# the test hook for monkeypatched backends.
_backend_memo: Optional[str] = None


def _cached_backend() -> str:
    global _backend_memo
    if _backend_memo is None:
        _backend_memo = jax.default_backend()
    return _backend_memo


def reset_backend_memo() -> None:
    global _backend_memo
    _backend_memo = None


def _kernel_feasible(q_shape, dtype) -> bool:
    """Structural floor for the fused kernel (what ``route='pallas'``
    may force): 4-D queries, a decode/verify-sized chunk, float query
    dtype.  The MXU/crossover conditions live in the ``*_supported``
    gates — they decide WINNING, this decides EXISTING."""
    from .pallas_decode import MAX_KERNEL_QUERY_CHUNK

    return (len(q_shape) == 4 and q_shape[2] <= MAX_KERNEL_QUERY_CHUNK
            and jnp.dtype(dtype) in _SUPPORTED_DTYPES)


def _bias_kernel_compatible(bias, b, h, lq, s) -> bool:
    """The kernel streams bias block-wise and needs the materialized
    4-D [B|1, H|1, Lq, S] layout; other broadcastable shapes keep the
    composition (the transformer decode paths pass ``q_pos`` instead of
    a bias, so this only ever gates external callers).  The shape rule
    itself lives with the kernel (``bias_streamable``) so routing and
    kernel validation cannot diverge."""
    if bias is None:
        return True
    from .pallas_decode import bias_streamable

    return bias_streamable(getattr(bias, "shape", ()), b, h, lq, s)


def _resolve_route(route, supported: bool, feasible: bool) -> bool:
    """True when this call takes the fused pallas kernel."""
    r = _route_stack()[-1] if route is None \
        else normalize_decode_route(route)
    if r == "composition":
        return False
    if r == "pallas":
        return feasible
    return supported


def _qpos_bias(q_pos, s_len: int, dtype):
    """The composition's additive mask from last-visible-key positions:
    [L] q_pos -> [1, 1, L, S] (aligned batch), [B, L] -> [B, 1, L, S]
    (slot-batched) — op-for-op the mask the transformer decode paths
    built inline before the routing seam existed, so the composition's
    jaxpr (and its compiled output) is unchanged."""
    qp = jnp.asarray(q_pos, jnp.int32)
    neg = jnp.asarray(jnp.finfo(jnp.float32).min, dtype)
    if qp.ndim == 1:
        allow = jnp.arange(s_len)[None, :] <= qp[:, None]
        return jnp.where(allow, 0.0, neg)[None, None]
    allow = jnp.arange(s_len)[None, None, :] <= qp[:, :, None]
    return jnp.where(allow, 0.0, neg)[:, None]


def _effective_qpos(q_pos, lengths, b: int, lq: int, s: int):
    """The kernel's [B, Lq] mask-index form of whatever masking the
    caller expressed: ``q_pos`` (per-query last visible key) and/or
    ``lengths`` (valid-token counts; key s is visible iff s < lengths,
    i.e. last visible = lengths - 1), combined by min.  With neither,
    every key is visible."""
    qp = None
    if q_pos is not None:
        qp = jnp.asarray(q_pos, jnp.int32)
        if qp.ndim == 1:
            qp = jnp.broadcast_to(qp[None, :], (b, lq))
        else:
            qp = jnp.broadcast_to(qp, (b, lq))
    if lengths is not None:
        ln = jnp.asarray(lengths, jnp.int32)
        if ln.ndim == 0:
            ln = jnp.broadcast_to(ln[None], (b,))
        lim = jnp.broadcast_to((ln - 1)[:, None], (b, lq))
        qp = lim if qp is None else jnp.minimum(qp, lim)
    if qp is None:
        qp = jnp.full((b, lq), s - 1, jnp.int32)
    return qp


def decode_attention_supported(q_shape, kv_len: int, dtype) -> bool:
    """Gate for the fused single-query/short-chunk pallas decode kernel
    (``ops.pallas_decode.decode_attention_kernel``): TPU backend, 4-D
    [B, H, Lq, D] with a short query chunk, MXU-tileable head_dim and a
    cache long enough to beat the fused XLA composition.  This is the
    "auto" route's decision; ``route="pallas"``/``"composition"``
    override it for tests and sweeps."""
    from .pallas_decode import MAX_KERNEL_QUERY_CHUNK

    if _cached_backend() != "tpu":
        return False
    if len(q_shape) != 4 or q_shape[2] > MAX_KERNEL_QUERY_CHUNK:
        return False
    if q_shape[3] not in (64, 128, 256):
        return False
    if kv_len < DECODE_FLASH_MIN_CACHE:
        return False
    return jnp.dtype(dtype) in _SUPPORTED_DTYPES


def decode_attention(q, k, v, bias=None, sm_scale: Optional[float] = None,
                     k_scale=None, v_scale=None, q_pos=None, route=None):
    """Decode-step attention: [B, H, Lq, D] queries against a FULL
    preallocated cache [B, H, S, D] (S = max_len), with ``bias`` masking
    the invalid tail (positions at or beyond the cache index) to -inf.

    Lq is the current chunk: 1 for autoregressive decode, spec_k+1 for
    a speculative VERIFY step (jit/speculative.py) — the verify chunk
    reuses this composition unchanged, which is why speculative logits
    equal plain decode logits up to reduction order, and why the
    single-query kernel gate below admits short chunks (Lq <= 8), not
    just Lq == 1.  The math is deliberately identical to the XLA
    fallback in
    ``F.scaled_dot_product_attention`` so cached and uncached logits
    agree to float-reduction noise.  Masked (garbage) cache positions
    contribute exp(-inf) == 0 to the softmax, so preallocation never
    changes the result, only the reduction shape — which XLA keeps
    shape-static across every decode step.

    ``k_scale``/``v_scale`` ([B, H, S] fp32) mark an int8-quantized
    cache: K/V arrive as int8 and are dequantized per head IN the
    composition (the HBM read is int8; the up-cast fuses into the score
    matmul).  The sm_scale default keys off the QUERY's head_dim, so the
    int8 path scores identically to fp32 up to quantization error.

    ``q_pos`` ([Lq] or [B, Lq] int32) expresses the causal-prefix mask
    as the last key position each query may attend — the structured
    form the decode-cache forwards pass so the fused kernel route can
    mask in-register instead of streaming a materialized bias; the
    composition builds the exact additive mask the callers used to
    build inline.  ``route`` overrides the ambient :func:`decode_route`
    ("auto" | "composition" | "pallas")."""
    d = q.shape[-1]
    if sm_scale is None:
        sm_scale = 1.0 / float(np.sqrt(d))
    s = k.shape[2]
    if _resolve_route(
            route,
            decode_attention_supported(q.shape, s, q.dtype)
            and _bias_kernel_compatible(bias, q.shape[0], q.shape[1],
                                        q.shape[2], s),
            _kernel_feasible(q.shape, q.dtype)):
        # fused pallas route (docs/DESIGN.md §5l): stream cache tiles
        # through VMEM with an online softmax — int8 tiles dequantize
        # in VMEM, so the HBM read stays int8 and the gathered fp32
        # cache is never materialized
        from .pallas_decode import decode_attention_kernel

        qp = _effective_qpos(q_pos, None, q.shape[0], q.shape[2], s)
        return decode_attention_kernel(
            q, k, v, qp, float(sm_scale), k_scale=k_scale,
            v_scale=v_scale, bias=bias,
            interpret=_cached_backend() != "tpu")
    if k_scale is not None:
        k = dequantize_kv(k, k_scale, q.dtype)
    if v_scale is not None:
        v = dequantize_kv(v, v_scale, q.dtype)
    if q_pos is not None:
        pos_bias = _qpos_bias(q_pos, s, q.dtype)
        bias = pos_bias if bias is None else bias + pos_bias
    scores = jnp.einsum("...qd,...kd->...qk", q, k) * jnp.asarray(
        sm_scale, q.dtype)
    if bias is not None:
        scores = scores + bias.astype(scores.dtype)
    weights = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("...qk,...kd->...qd", weights, v)


# ---------------------------------------------------------------------------
# paged decode attention: block-table KV cache (vLLM scheme, static shapes)
# ---------------------------------------------------------------------------


def paged_decode_attention_supported(q_shape, block_size: int,
                                     num_blocks: int, dtype) -> bool:
    """Gate for the fused pallas PAGED decode kernel
    (``ops.pallas_decode.paged_decode_attention_kernel``), mirroring
    ``decode_attention_supported``: TPU backend, short query chunk,
    MXU-tileable head_dim, sublane-aligned block_size, and a pool big
    enough that the hand-tiled gather kernel beats the XLA
    gather+composition.  The "auto" route's decision;
    ``route="pallas"``/``"composition"`` override it."""
    from .pallas_decode import MAX_KERNEL_QUERY_CHUNK

    if _cached_backend() != "tpu":
        return False
    if len(q_shape) != 4 or q_shape[2] > MAX_KERNEL_QUERY_CHUNK:
        return False
    if q_shape[3] not in (64, 128, 256):
        return False
    if block_size < 8 or block_size % 8 != 0:
        return False
    if block_size * num_blocks < DECODE_FLASH_MIN_CACHE:
        return False
    return jnp.dtype(dtype) in _SUPPORTED_DTYPES


def paged_decode_attention(q, k_pool, v_pool, table, lengths=None, bias=None,
                           sm_scale: Optional[float] = None,
                           k_scale=None, v_scale=None, q_pos=None,
                           route=None):
    """Decode-step attention against a BLOCK-TABLE KV cache.

    ``q``: [B, H, Lq, D] queries (Lq = 1 for autoregressive decode,
    spec_k+1 for a speculative verify chunk — same reuse discipline as
    ``decode_attention``).
    ``k_pool``/``v_pool``: [num_blocks, H, block_size, D] global block
    pools shared by every row.  ``table``: [B, max_blocks] int32 — row
    b's logical block j lives in physical pool row ``table[b, j]``
    (physical block 0 is by convention a scratch/trash block that
    unmapped logical blocks point at).  ``lengths``: optional scalar or
    [B] int32 count of VALID tokens per row; positions at or beyond it
    are masked to -inf.  ``bias`` is an extra additive mask
    broadcastable to [B, H, Lq, S] with S = max_blocks * block_size
    (callers that already know their causal-prefix mask pass it here and
    skip ``lengths``).

    ``k_scale``/``v_scale`` ([num_blocks, H, block_size] fp32) mark an
    int8-quantized pool: the per-head scales RIDE WITH their blocks
    (gathered through the same table, so a remapped block carries its
    own scales) and dequantization happens on the gathered rows — the
    pool read stays int8.

    All shapes are static — only the TABLE VALUES vary per step — so one
    XLA compilation serves every allocation state, the same
    compiler-first caching discipline as the dense ``decode_attention``
    (which this reduces to after the gather: the math is shared so paged
    and dense logits agree to float-reduction noise).  The pool rows a
    step can READ are exactly the mapped blocks, so cache HBM scales
    with allocated tokens, not max_len × rows.

    ``q_pos``/``route`` as in :func:`decode_attention`.  On the fused
    pallas route the gather below never happens: the kernel's grid
    walks the table itself (scalar-prefetched block indices feed the
    DMA), streams pool blocks into VMEM, dequantizes int8 rows there,
    and runs the online softmax — so the composition's HBM-materialized
    [B, H, S, D] gathered (and, for int8, fp32-up-cast) K/V is exactly
    the traffic the kernel deletes.
    """
    b, mb = table.shape
    nb, h, bs, d = k_pool.shape
    s = mb * bs
    if sm_scale is None:
        sm_scale = 1.0 / float(np.sqrt(q.shape[-1]))
    if _resolve_route(
            route,
            paged_decode_attention_supported(q.shape, bs, nb, q.dtype)
            and _bias_kernel_compatible(bias, b, q.shape[1], q.shape[2],
                                        s),
            _kernel_feasible(q.shape, q.dtype)):
        from .pallas_decode import paged_decode_attention_kernel

        qp = _effective_qpos(q_pos, lengths, b, q.shape[2], s)
        return paged_decode_attention_kernel(
            q, k_pool, v_pool, jnp.asarray(table, jnp.int32), qp,
            float(sm_scale), k_scale=k_scale, v_scale=v_scale,
            bias=bias, interpret=_cached_backend() != "tpu")
    # gather the row's blocks: [B, MB, H, bs, D] -> [B, H, MB*bs, D];
    # XLA lowers the fancy-index to one gather over the pool's leading
    # axis, the only data-dependent op in the step
    tbl = jnp.asarray(table, jnp.int32)
    k = k_pool[tbl].transpose(0, 2, 1, 3, 4).reshape(b, h, s, d)
    v = v_pool[tbl].transpose(0, 2, 1, 3, 4).reshape(b, h, s, d)
    ks = vs = None
    if k_scale is not None:
        ks = k_scale[tbl].transpose(0, 2, 1, 3).reshape(b, h, s)
    if v_scale is not None:
        vs = v_scale[tbl].transpose(0, 2, 1, 3).reshape(b, h, s)
    if lengths is not None:
        lengths = jnp.asarray(lengths, jnp.int32)
        if lengths.ndim == 0:
            allow = (jnp.arange(s) < lengths)[None, None, None, :]
        else:
            allow = (jnp.arange(s)[None, :]
                     < lengths[:, None])[:, None, None, :]
        neg = jnp.asarray(jnp.finfo(jnp.float32).min, q.dtype)
        len_bias = jnp.where(allow, 0.0, neg)
        bias = len_bias if bias is None else bias + len_bias
    if q_pos is not None:
        pos_bias = _qpos_bias(q_pos, s, q.dtype)
        bias = pos_bias if bias is None else bias + pos_bias
    # route pinned to the composition: the kernel decision was made
    # above on the PAGED shapes — re-routing the gathered dense arrays
    # would run the dense kernel on K/V already materialized in HBM,
    # the exact traffic the kernel exists to avoid
    return decode_attention(q, k, v, bias=bias, sm_scale=sm_scale,
                            k_scale=ks, v_scale=vs, route="composition")


# id(mask) → (weakref(mask), verdict); masks are immutable jax arrays built
# once per model / per trace, so identity caching removes the repeated
# device→host readback.  Weakrefs keep the cache from pinning [L, L] masks
# after their models are freed, and a dead ref also invalidates the entry if
# a new allocation recycles the id (id-only keys are unsound).
_detect_cache: dict = {}
_DETECT_CACHE_MAX = 64


_pad_detect_cache: dict = {}


def detect_padding_additive_mask(mask):
    """[B, 1, 1, Lk] additive padding mask → [B, Lk] bool validity, else
    None.  Catches the standard paddle convention (0 = keep, big-negative =
    pad) so the flash path can use O(L) segment lanes instead of
    broadcasting the bias to [B, H, Lq, Lk] — the exact O(L²·H) HBM
    materialization the kernel exists to avoid.  Only the [B, 1, 1, Lk]
    layout is claimed: a 2-D additive mask means [Lq, Lk] in paddle, which
    is per-query, not key padding.  Concrete masks only; traced masks go
    down the general bias path.  Verdicts are identity-cached like
    ``detect_causal_additive_mask`` — masks are typically built once per
    model, and the readback is a blocking device→host copy."""
    if mask is None or isinstance(mask, jax.core.Tracer):
        return None
    shape = getattr(mask, "shape", None)
    if shape is None or len(shape) != 4 or shape[1] != 1 or shape[2] != 1:
        return None
    import weakref

    key = id(mask)
    hit = _pad_detect_cache.get(key)
    if hit is not None and hit[0]() is mask:
        return hit[1]
    m = np.asarray(mask)[:, 0, 0, :]
    if m.dtype == np.bool_:
        valid = m
    else:
        neg = np.finfo(np.float32).min / 2
        ok = m == 0
        pad = m <= neg
        valid = None if not np.all(ok | pad) else ok  # else: general bias
    try:
        ref = weakref.ref(mask)
    except TypeError:  # pragma: no cover - non-weakrefable array type
        return valid
    if len(_pad_detect_cache) >= _DETECT_CACHE_MAX:
        dead = [k for k, v in _pad_detect_cache.items() if v[0]() is None]
        for k in dead:
            del _pad_detect_cache[k]
        if len(_pad_detect_cache) >= _DETECT_CACHE_MAX:
            _pad_detect_cache.clear()
    _pad_detect_cache[key] = (ref, valid)
    return valid


def detect_causal_additive_mask(mask, seq_len: Optional[int] = None) -> bool:
    """True when ``mask`` is a concrete 2-D additive causal mask (0 on/below
    the diagonal, strictly large-negative above) matching ``seq_len`` — lets
    the kernel's causal fast path replace a materialized mask without
    changing the paddle API.  This also covers jitted callers whose mask is
    built from static shapes (constant-folded to a concrete array inside the
    trace, e.g. TransformerLM._causal_mask); masks that are runtime inputs
    arrive as tracers and safely skip detection."""
    if mask is None or isinstance(mask, jax.core.Tracer):
        return False
    if getattr(mask, "ndim", 0) != 2 or mask.shape[-1] != mask.shape[-2]:
        return False
    l = mask.shape[0]
    if l < 2:  # 1x1 has an empty upper triangle: vacuously "causal"
        return False
    if seq_len is not None and l != seq_len:
        return False  # broadcast-shaped masks keep their loud-error path
    import weakref

    key = id(mask)
    hit = _detect_cache.get(key)
    if hit is not None and hit[0]() is mask:
        return hit[1]
    m = np.asarray(mask)
    allow = np.tril(np.ones((l, l), dtype=bool))  # one L*L bool, no indices
    lower_ok = np.all(np.where(allow, m, 0) == 0)
    upper_ok = np.all(np.where(allow, np.finfo(np.float32).min, m)
                      <= np.finfo(np.float32).min / 2)
    verdict = bool(lower_ok and upper_ok)
    try:
        ref = weakref.ref(mask)
    except TypeError:  # pragma: no cover - non-weakrefable array type
        return verdict
    if len(_detect_cache) >= _DETECT_CACHE_MAX:
        dead = [k for k, v in _detect_cache.items() if v[0]() is None]
        for k in dead:
            del _detect_cache[k]
        if len(_detect_cache) >= _DETECT_CACHE_MAX:
            _detect_cache.clear()
    _detect_cache[key] = (ref, verdict)
    return verdict
