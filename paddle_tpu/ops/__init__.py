"""``paddle_tpu.ops`` — fused TPU kernels (pallas).

Reference parity: the reference's hand-fused CUDA ops —
``operators/fused/fused_attention_op.cu``, ``fused_gate_attention_op`` and the
``incubate.nn.FusedMultiHeadAttention`` surface.  Here the hot ops are pallas
TPU kernels (SURVEY §7 MFU target): flash attention keeps the [L, L] score
matrix out of HBM entirely, which is the bandwidth win that decides MFU at
long sequence length.
"""
from .flash_attention import (  # noqa: F401
    decode_attention,
    decode_attention_supported,
    dequantize_kv,
    flash_attention,
    flash_attention_supported,
    paged_decode_attention,
    paged_decode_attention_supported,
    quantize_kv,
)

__all__ = ["flash_attention", "flash_attention_supported",
           "decode_attention", "decode_attention_supported",
           "paged_decode_attention", "paged_decode_attention_supported",
           "quantize_kv", "dequantize_kv"]
