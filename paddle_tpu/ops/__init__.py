"""``paddle_tpu.ops`` — fused TPU kernels (pallas).

Reference parity: the reference's hand-fused CUDA ops —
``operators/fused/fused_attention_op.cu``, ``fused_gate_attention_op`` and the
``incubate.nn.FusedMultiHeadAttention`` surface.  Here the hot ops are pallas
TPU kernels (SURVEY §7 MFU target): flash attention keeps the [L, L] score
matrix out of HBM entirely, which is the bandwidth win that decides MFU at
long sequence length.
"""
from .flash_attention import (  # noqa: F401
    DECODE_ROUTES,
    decode_attention,
    decode_attention_supported,
    decode_route,
    dequantize_kv,
    flash_attention,
    flash_attention_supported,
    normalize_decode_route,
    paged_decode_attention,
    paged_decode_attention_supported,
    quantize_kv,
    reset_backend_memo,
)
from .pallas_decode import (  # noqa: F401
    decode_attention_kernel,
    paged_decode_attention_kernel,
)

__all__ = ["flash_attention", "flash_attention_supported",
           "decode_attention", "decode_attention_supported",
           "paged_decode_attention", "paged_decode_attention_supported",
           "quantize_kv", "dequantize_kv",
           "decode_attention_kernel", "paged_decode_attention_kernel",
           "decode_route", "normalize_decode_route", "DECODE_ROUTES",
           "reset_backend_memo"]
