"""Fused pallas paged/dense decode-attention kernel (docs/DESIGN.md §5l).

The decode-family steps are cache-bandwidth-bound: one (or a short
chunk of) query positions attend a long KV cache, and the XLA
composition in ``flash_attention.py`` pays for that in HBM round trips
the compiler cannot fuse away ("Operator Fusion in XLA", PAPERS.md):
the paged path's data-dependent table gather MATERIALIZES the gathered
``[B, H, S, D]`` K/V in HBM before attention, and the int8 path's
dequantize up-casts the whole gathered cache to fp32 there too — 4-8x
the bytes the cache actually holds.

This kernel crosses both boundaries by hand.  Per ``(batch row, head,
logical block)`` grid step it

- reads the row's block table (a scalar-prefetch operand, so the block
  index feeds the DMA descriptor *before* the body runs) and streams
  that ONE physical K/V block from the pool in HBM into VMEM;
- dequantizes int8 rows in VMEM — the per-head scales are gathered
  through the SAME table row, so a remapped block always carries its
  own scales;
- applies the lengths/bias masking in-register (``q_pos`` names each
  query's last visible key position; an optional additive bias streams
  block-by-block alongside K/V);
- accumulates attention with an ONLINE softmax across the block axis
  (running max / normalizer / weighted-V in VMEM scratch that persists
  over the sequential grid), so neither the gathered fp32 K/V nor the
  ``[Lq, S]`` score row ever exists in HBM.

``decode_attention_kernel`` is the dense-cache variant on the same
inner loop: the "table" is the identity walk of the ``[B, H, S, D]``
buffer, chunked into sequence tiles.

Shapes are static; query chunks are short (``Lq <= 8`` — single-token
decode and the speculative verify chunk).  ``interpret=True`` runs the
kernel under the pallas interpreter so the SAME body is tier-1-testable
on CPU: numeric identity against the composition is pinned without a
TPU (tests/test_pallas_decode.py), while the routing gates in
``flash_attention.py`` keep compiled-mode engagement TPU-only and
measured-crossover honest.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..core.errors import InvalidArgumentError

__all__ = ["decode_attention_kernel", "paged_decode_attention_kernel",
           "MAX_KERNEL_QUERY_CHUNK", "bias_streamable"]

# The longest query chunk the kernel accepts: 1 for autoregressive
# decode, spec_k+1 for a speculative verify chunk.  Longer chunks are
# prefill-shaped work — the flash_attention kernel's territory — and
# the routing layer never sends them here.
MAX_KERNEL_QUERY_CHUNK = 8

# Finite floor for the running max: masked scores are -inf, so with an
# all-masked prefix the running max stays at this floor and
# exp(-inf - floor) == 0 keeps masked positions out of the normalizer
# (a raw -inf running max would turn exp(-inf - -inf) into NaN).
_M_FLOOR = -1e30


def _dense_seq_block(s: int) -> int:
    """Sequence tile for the dense variant: the largest sublane-friendly
    power of two dividing ``s`` (falling back to one whole-sequence tile
    when nothing divides — correctness never depends on the tile)."""
    for cand in (512, 256, 128, 64, 32, 16, 8):
        if s % cand == 0:
            return cand
    return s


def bias_streamable(bias_shape, b: int, h: int, lq: int, s: int) -> bool:
    """Whether an additive bias can stream block-wise through the
    kernel: 4-D [B|1, H|1, Lq, S].  THE shape rule — the routing layer
    (flash_attention._bias_kernel_compatible) and the kernel's own
    validation both read it, so they cannot diverge."""
    return (len(bias_shape) == 4 and bias_shape[0] in (1, b)
            and bias_shape[1] in (1, h) and bias_shape[2] == lq
            and bias_shape[3] == s)


def _check_common(q, q_pos, bias, s: int):
    if q.ndim != 4:
        raise InvalidArgumentError(
            "pallas decode kernel needs 4-D [B, H, Lq, D] queries, got "
            "shape %r" % (tuple(q.shape),))
    b, h, lq, _ = q.shape
    if lq > MAX_KERNEL_QUERY_CHUNK:
        raise InvalidArgumentError(
            "pallas decode kernel takes query chunks of at most %d "
            "positions (decode steps and speculative verify chunks), "
            "got Lq=%d — long chunks are prefill work"
            % (MAX_KERNEL_QUERY_CHUNK, lq))
    if q_pos.ndim != 2 or q_pos.shape[0] != b or q_pos.shape[1] != lq:
        raise InvalidArgumentError(
            "q_pos must be [B, Lq] int32 last-visible-key positions "
            "(got %r for q %r)" % (tuple(q_pos.shape), tuple(q.shape)))
    if bias is not None:
        bs_ = getattr(bias, "shape", ())
        if not bias_streamable(bs_, b, h, lq, s):
            raise InvalidArgumentError(
                "kernel bias must be 4-D broadcastable to [B, H, Lq, S]"
                " = %r (got %r); other shapes take the composition path"
                % ((b, h, lq, s), tuple(bs_)))


def _make_body(n_scalar: int, lq: int, bs: int, sm_scale: float,
               quant: bool, has_bias: bool):
    """The shared inner loop.  Ref order after the ``n_scalar``
    scalar-prefetch refs (q_pos always last among them): q, k, v,
    [k_scale, v_scale,] [bias,] out, then m/l/acc VMEM scratch."""

    def body(*refs):
        qpos_ref = refs[n_scalar - 1]
        q_ref, k_ref, v_ref = refs[n_scalar:n_scalar + 3]
        i = n_scalar + 3
        ks_ref = vs_ref = bias_ref = None
        if quant:
            ks_ref, vs_ref = refs[i:i + 2]
            i += 2
        if has_bias:
            bias_ref = refs[i]
            i += 1
        o_ref, m_ref, l_ref, acc_ref = refs[i:i + 4]

        bi = pl.program_id(0)
        j = pl.program_id(2)

        @pl.when(j == 0)
        def _():
            m_ref[...] = jnp.full_like(m_ref, _M_FLOOR)
            l_ref[...] = jnp.zeros_like(l_ref)
            acc_ref[...] = jnp.zeros_like(acc_ref)

        qb = q_ref[0, 0].astype(jnp.float32)            # [Lq, D]
        kb = k_ref[0, 0]                                # [bs, D]
        vb = v_ref[0, 0]
        if quant:
            # VMEM dequant: the HBM read above was int8 — the up-cast
            # happens here, on one block, never on the gathered cache
            kb = kb.astype(jnp.float32) * ks_ref[0, 0][:, None]
            vb = vb.astype(jnp.float32) * vs_ref[0, 0][:, None]
        else:
            kb = kb.astype(jnp.float32)
            vb = vb.astype(jnp.float32)
        s = jax.lax.dot_general(
            qb, kb, dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale  # [Lq, bs]
        if has_bias:
            s = s + bias_ref[0, 0].astype(jnp.float32)
        # mask keys past each query's position (lengths masking, stale
        # table rows, the scratch block's garbage — all arrive as q_pos)
        pos = j * bs + jax.lax.broadcasted_iota(jnp.int32, (lq, bs), 1)
        allow = pos <= qpos_ref[bi][:, None]
        s = jnp.where(allow, s, -jnp.inf)
        # online softmax: rescale the running sums by exp(m_old - m_new)
        m_prev = m_ref[...]                             # [Lq, 1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)                          # masked -> 0
        alpha = jnp.exp(m_prev - m_new)
        l_ref[...] = alpha * l_ref[...] + jnp.sum(p, axis=1,
                                                  keepdims=True)
        acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
            p, vb, dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[...] = m_new

        @pl.when(j == pl.num_programs(2) - 1)
        def _():
            l = l_ref[...]
            # a row with no visible key (q_pos < 0 everywhere) emits 0
            # rather than NaN; real decode rows always see position 0
            l = jnp.where(l == 0.0, 1.0, l)
            o_ref[0, 0] = (acc_ref[...] / l).astype(o_ref.dtype)

    return body


def _scratch(lq: int, d: int):
    return [pltpu.VMEM((lq, 1), jnp.float32),   # running max
            pltpu.VMEM((lq, 1), jnp.float32),   # running normalizer
            pltpu.VMEM((lq, d), jnp.float32)]   # weighted-V accumulator


def _bias_index_map(bias_shape, paged: bool):
    bb, hb = bias_shape[0] > 1, bias_shape[1] > 1
    if paged:
        return lambda b, h, j, tbl, qp: (b if bb else 0,
                                         h if hb else 0, 0, j)
    return lambda b, h, j, qp: (b if bb else 0, h if hb else 0, 0, j)


@functools.partial(jax.jit, static_argnames=("sm_scale", "interpret"))
def _paged_call(q, k_pool, v_pool, table, q_pos, k_scale, v_scale, bias,
                sm_scale, interpret):
    b, h, lq, d = q.shape
    _, _, bs, _ = k_pool.shape
    mb = table.shape[1]
    quant = k_scale is not None
    has_bias = bias is not None

    def pool_map(bb, hh, j, tbl, qp):
        return (tbl[bb, j], hh, 0, 0)

    def scale_map(bb, hh, j, tbl, qp):
        return (tbl[bb, j], hh, 0)

    in_specs = [
        pl.BlockSpec((1, 1, lq, d), lambda bb, hh, j, tbl, qp:
                     (bb, hh, 0, 0)),
        pl.BlockSpec((1, 1, bs, d), pool_map),
        pl.BlockSpec((1, 1, bs, d), pool_map),
    ]
    args = [q, k_pool, v_pool]
    if quant:
        in_specs += [pl.BlockSpec((1, 1, bs), scale_map)] * 2
        args += [k_scale, v_scale]
    if has_bias:
        in_specs.append(pl.BlockSpec((1, 1, lq, bs),
                                     _bias_index_map(bias.shape, True)))
        args.append(bias)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, h, mb),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, lq, d), lambda bb, hh, j, tbl, qp:
                               (bb, hh, 0, 0)),
        scratch_shapes=_scratch(lq, d))
    return pl.pallas_call(
        _make_body(2, lq, bs, sm_scale, quant, has_bias),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, h, lq, d), q.dtype),
        interpret=interpret,
    )(table, q_pos, *args)


def paged_decode_attention_kernel(q, k_pool, v_pool, table, q_pos,
                                  sm_scale: float,
                                  k_scale=None, v_scale=None, bias=None,
                                  interpret: bool = False):
    """Fused paged decode attention: ``q`` [B, H, Lq, D] against a
    block-table pool [num_blocks, H, bs, D], never materializing the
    gathered K/V.

    ``table``: [B, max_blocks] int32 — fed as a scalar-prefetch operand
    so each grid step's DMA streams pool row ``table[b, j]`` directly.
    ``q_pos``: [B, Lq] int32, the last key position each query may
    attend (the causal-prefix / lengths mask in index form; stale table
    rows and the scratch block sit past it and are never read into the
    softmax).  ``k_scale``/``v_scale`` ([num_blocks, H, bs] fp32) mark
    an int8 pool; dequantization happens in VMEM on the streamed block.
    ``bias``: optional additive [B|1, H|1, Lq, S] streamed block-wise.
    """
    nb, h, bs, d = k_pool.shape
    s = table.shape[1] * bs
    _check_common(q, q_pos, bias, s)
    if table.ndim != 2 or table.shape[0] != q.shape[0]:
        raise InvalidArgumentError(
            "table must be [B, max_blocks] int32 (got %r for q %r)"
            % (tuple(table.shape), tuple(q.shape)))
    if (k_scale is None) != (v_scale is None):
        raise InvalidArgumentError(
            "int8 pools carry BOTH k_scale and v_scale (got one)")
    return _paged_call(q, k_pool, v_pool,
                       jnp.asarray(table, jnp.int32),
                       jnp.asarray(q_pos, jnp.int32),
                       k_scale, v_scale, bias,
                       float(sm_scale), bool(interpret))


@functools.partial(jax.jit, static_argnames=("sm_scale", "interpret"))
def _dense_call(q, k, v, q_pos, k_scale, v_scale, bias, sm_scale,
                interpret):
    b, h, lq, d = q.shape
    s = k.shape[2]
    bs = _dense_seq_block(s)
    mb = s // bs
    quant = k_scale is not None
    has_bias = bias is not None

    def seq_map(bb, hh, j, qp):
        return (bb, hh, j, 0)

    in_specs = [
        pl.BlockSpec((1, 1, lq, d), lambda bb, hh, j, qp:
                     (bb, hh, 0, 0)),
        pl.BlockSpec((1, 1, bs, d), seq_map),
        pl.BlockSpec((1, 1, bs, d), seq_map),
    ]
    args = [q, k, v]
    if quant:
        in_specs += [pl.BlockSpec((1, 1, bs), lambda bb, hh, j, qp:
                                  (bb, hh, j))] * 2
        args += [k_scale, v_scale]
    if has_bias:
        in_specs.append(pl.BlockSpec((1, 1, lq, bs),
                                     _bias_index_map(bias.shape, False)))
        args.append(bias)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(b, h, mb),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, lq, d), lambda bb, hh, j, qp:
                               (bb, hh, 0, 0)),
        scratch_shapes=_scratch(lq, d))
    return pl.pallas_call(
        _make_body(1, lq, bs, sm_scale, quant, has_bias),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, h, lq, d), q.dtype),
        interpret=interpret,
    )(q_pos, *args)


def decode_attention_kernel(q, k, v, q_pos, sm_scale: float,
                            k_scale=None, v_scale=None, bias=None,
                            interpret: bool = False):
    """Dense-cache variant of the fused decode kernel: the same online
    softmax inner loop over sequence tiles of a preallocated
    [B, H, S, D] cache (``k_scale``/``v_scale`` [B, H, S] mark the int8
    cache; dequant in VMEM).  ``q_pos``/``bias`` as in the paged
    variant with S = the cache length."""
    if k.ndim != 4:
        raise InvalidArgumentError(
            "dense kernel cache must be [B, H, S, D], got %r"
            % (tuple(k.shape),))
    _check_common(q, q_pos, bias, k.shape[2])
    if (k_scale is None) != (v_scale is None):
        raise InvalidArgumentError(
            "int8 caches carry BOTH k_scale and v_scale (got one)")
    return _dense_call(q, k, v, jnp.asarray(q_pos, jnp.int32),
                       k_scale, v_scale, bias,
                       float(sm_scale), bool(interpret))
