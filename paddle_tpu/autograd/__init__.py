"""``paddle_tpu.autograd`` — autograd facade.

Reference parity: ``python/paddle/autograd/`` + the dygraph engines
(``imperative/basic_engine.cc``, ``partial_grad_engine.cc``).  Eager mode uses
the tape in ``framework.engine``; jitted code uses ``jax.grad`` directly (see
``paddle_tpu.jit``).
"""
from ..framework.engine import backward, grad, is_grad_enabled, no_grad, set_grad_enabled, enable_grad  # noqa: F401

__all__ = ["backward", "grad", "no_grad", "enable_grad", "is_grad_enabled", "set_grad_enabled", "PyLayer", "PyLayerContext"]


class PyLayer:
    """Custom-autograd extension point (reference: paddle.autograd.PyLayer,
    python/paddle/autograd/py_layer.py).

    Subclass with static ``forward(ctx, *args)`` and ``backward(ctx, *grads)``.
    Implemented as a recorded op whose pullback calls the user's backward.
    """

    @staticmethod
    def forward(ctx, *args, **kwargs):  # pragma: no cover - interface
        raise NotImplementedError

    @staticmethod
    def backward(ctx, *grads):  # pragma: no cover - interface
        raise NotImplementedError

    @classmethod
    def apply(cls, *args, **kwargs):
        import jax
        import jax.numpy as jnp

        from ..framework import engine
        from ..framework.tensor import Tensor

        class _Ctx(PyLayerContext):
            def __init__(self):
                self._saved = ()

            def save_for_backward(self, *tensors):
                self._saved = tensors

            def saved_tensor(self):
                return self._saved

        ctx = _Ctx()
        out = cls.forward(ctx, *args, **kwargs)
        single = isinstance(out, Tensor)
        outs = [out] if single else list(out)

        diff_inputs = [
            a for a in args if isinstance(a, Tensor) and not a.stop_gradient
        ]
        if engine.is_grad_enabled() and diff_inputs:
            n_in = len(diff_inputs)

            def vjp_fn(cotangents):
                grads = cls.backward(ctx, *[
                    Tensor(c) if not isinstance(c, Tensor) else c for c in cotangents
                ])
                if isinstance(grads, Tensor):
                    grads = (grads,)
                vals = [g._value if isinstance(g, Tensor) else g for g in grads]
                if len(vals) != n_in:
                    raise ValueError(
                        "PyLayer.backward returned %d grads for %d differentiable inputs"
                        % (len(vals), n_in)
                    )
                return vals

            out_avals = [(tuple(t.shape), t.dtype) for t in outs]
            leaves, treedef = jax.tree_util.tree_flatten(list(range(len(outs))))
            node = engine.GradNode(
                vjp_fn, diff_inputs, treedef, out_avals, op_name=cls.__name__
            )
            for k, t in enumerate(outs):
                t.stop_gradient = False
                t._node = node
                t._leaf_idx = k
        return out


class PyLayerContext:
    """Type of the ``ctx`` object passed to PyLayer.forward/backward
    (py_layer.py PyLayerContext parity).  Provided for isinstance checks
    and documentation; PyLayer builds instances internally."""

    def save_for_backward(self, *tensors):
        self.saved_tensor_list = list(tensors)

    def saved_tensor(self):
        return list(getattr(self, "saved_tensor_list", ()))
