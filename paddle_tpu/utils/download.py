"""Pretrained-weight / archive path resolution (``paddle.utils.download``).

Reference: ``python/paddle/utils/download.py:66-265``. Zero-egress
build: instead of fetching, these resolve the CONVENTIONAL cache path
the reference's downloader would have produced (``~/.cache/paddle/hapi/
weights`` for weights) and, when the file is already there, md5-verify
and optionally decompress it exactly like the reference; a cache miss
raises with the precise path to place the file at.
"""
from __future__ import annotations

import hashlib
import os
import os.path as osp
import tarfile
import zipfile

from ..core.errors import InvalidArgumentError

__all__ = ["get_weights_path_from_url"]

WEIGHTS_HOME = osp.expanduser(osp.join("~", ".cache", "paddle", "hapi",
                                       "weights"))


def is_url(path) -> bool:
    """True for http/https locations (``download.py:66``)."""
    return isinstance(path, str) and path.startswith(("http://", "https://"))


def _md5check(fullname, md5sum=None) -> bool:
    if md5sum is None:
        return True
    h = hashlib.md5()
    with open(fullname, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest() == md5sum


def _extraction_plan(fullpath: str, names):
    """(target_root, extract_into) for an archive's contents.

    Single shared top-level directory (the reference's
    ``_is_a_single_dir`` case) → that directory, extracting beside the
    archive; anything else (flat files, multiple roots, ``./``-prefixed
    members) → a directory named after the archive stem, extracting INTO
    it — so the returned root is always a real extraction root, never
    the cache root or the archive itself."""
    parent = osp.dirname(fullpath)

    def _strip_dot_slash(n):
        # strip only literal leading "./" prefixes: lstrip("./") strips a
        # character SET and would mangle names like "..data/x"
        while n.startswith("./"):
            n = n[2:]
        return n

    clean = [s for s in (_strip_dot_slash(n) for n in names) if s]
    roots = {n.split("/")[0] for n in clean}
    if len(roots) == 1 and all("/" in n for n in clean):
        target = osp.join(parent, next(iter(roots)))
        return target, parent
    stem = osp.basename(fullpath)
    for suf in (".tar.gz", ".tgz", ".tar", ".zip", ".gz"):
        if stem.endswith(suf):
            stem = stem[:-len(suf)]
            break
    target = osp.join(parent, stem)
    return target, target


def _decompress(fullpath: str) -> str:
    """Unpack a tar/zip once; re-calls short-circuit when the extracted
    root already exists (the reference's run-once behavior)."""
    if tarfile.is_tarfile(fullpath):
        with tarfile.open(fullpath) as tf:
            target, into = _extraction_plan(fullpath, tf.getnames())
            if not osp.exists(target):
                tf.extractall(into, filter="data")
    elif zipfile.is_zipfile(fullpath):
        with zipfile.ZipFile(fullpath) as zf:
            target, into = _extraction_plan(fullpath, zf.namelist())
            if not osp.exists(target):
                zf.extractall(into)
    else:
        return fullpath
    return target


def get_path_from_url(url, root_dir, md5sum=None, check_exist=True,
                      decompress=True, method="get"):
    """Resolve ``url`` to its conventional path under ``root_dir``.

    The file must already be there (no-egress build); it is ALWAYS
    md5-verified when ``md5sum`` is given (``check_exist=False`` — the
    reference's force-redownload mode — cannot re-fetch here, so it
    degrades to the same verify), and tar/zip archives are decompressed
    once, matching ``download.py:121``'s post-download behavior.
    """
    if not is_url(url):
        raise InvalidArgumentError("downloading from %r: not a url" % url)
    fullpath = osp.join(root_dir, url.split("/")[-1])
    if not osp.exists(fullpath):
        raise InvalidArgumentError(
            "no-egress build cannot download %s; place the file at %s"
            % (url, fullpath))
    if not _md5check(fullpath, md5sum):
        raise InvalidArgumentError(
            "%s exists but fails md5 verification (want %s)"
            % (fullpath, md5sum))
    if decompress and (tarfile.is_tarfile(fullpath)
                       or zipfile.is_zipfile(fullpath)):
        fullpath = _decompress(fullpath)
    return fullpath


def get_weights_path_from_url(url, md5sum=None):
    """Conventional local path of a pretrained-weights url
    (``download.py:75``); the file must be pre-placed under
    ``~/.cache/paddle/hapi/weights``."""
    os.makedirs(WEIGHTS_HOME, exist_ok=True)
    return get_path_from_url(url, WEIGHTS_HOME, md5sum)
