"""utils/unique_name.py parity: process-wide unique name generator with
guard contexts (the reference's UniqueNameGenerator over fluid cores)."""
from __future__ import annotations

import contextlib
import threading

__all__ = ["generate", "guard", "switch"]

_lock = threading.Lock()


class _Generator:
    def __init__(self):
        self.ids = {}

    def __call__(self, key: str) -> str:
        with _lock:
            n = self.ids.get(key, 0)
            self.ids[key] = n + 1
        return "%s_%d" % (key, n)


_generator = _Generator()


def generate(key: str) -> str:
    return _generator(key)


def switch(new_generator=None):
    """Swap the active generator, returning the previous one."""
    global _generator
    old = _generator
    _generator = new_generator or _Generator()
    return old


@contextlib.contextmanager
def guard(new_generator=None):
    old = switch(new_generator)
    try:
        yield
    finally:
        switch(old)
