"""JIT-compiled C++ custom ops (reference:
python/paddle/utils/cpp_extension/ + paddle/fluid/framework/custom_operator.cc
/ paddle/extension.h).

TPU-first position: device kernels belong to XLA/pallas
(``incubate.register_custom_op``); what C++ extensions buy on this stack is
*host* compute — tokenizers, feature hashing, decoders — so ``load``
compiles the sources with the system toolchain into a shared library and
registers each exported function as a framework op whose implementation is
a ``jax.pure_callback`` into the C++ code.  The ops are taped (eager
backward via an optional python ``backward``) and trace-safe (callback
works under jit).

C ABI convention (this stack's ``paddle/extension.h`` analog, see
``extension_header()``): each op is

    extern "C" void <name>(const float** ins, const long long** shapes,
                           const int* ndims, int n_ins, float* out);

operating on contiguous float32 buffers.  The python side supplies the
output shape rule (``out_shape``), mirroring the reference's InferShapeFn
registration.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import tempfile
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from ..core.errors import InvalidArgumentError

__all__ = ["load", "extension_header", "CppExtension", "get_build_directory"]

_HEADER = """\
// paddle_tpu extension header (paddle/extension.h analog, host-op C ABI)
#pragma once
#include <cstdint>
#define PT_OP(name) \\
  extern "C" __attribute__((visibility("default"))) void name( \\
      const float** ins, const long long** shapes, const int* ndims, \\
      int n_ins, float* out)
"""


def extension_header() -> str:
    """The C++ header text user sources can #include (written next to the
    sources by ``load``)."""
    return _HEADER


def get_build_directory() -> str:
    d = os.environ.get("PADDLE_EXTENSION_DIR",
                       os.path.join(tempfile.gettempdir(),
                                    "paddle_tpu_extensions"))
    os.makedirs(d, exist_ok=True)
    return d


class CppExtension:
    """setup()-style sources bundle (cpp_extension.CppExtension parity)."""

    def __init__(self, sources: Sequence[str], name: Optional[str] = None,
                 extra_compile_args=None, **kwargs):
        self.sources = list(sources)
        self.name = name
        self.extra_compile_args = extra_compile_args or []


def _compile(name: str, sources: Sequence[str], extra_flags: Sequence[str],
             build_dir: str, verbose: bool) -> str:
    # unique per-build output: dlopen caches by path, so overwriting one
    # lib<name>.so would hand reloads the previously mapped machine code
    import hashlib

    digest = hashlib.sha1()
    for src in sources:
        with open(src, "rb") as f:
            digest.update(f.read())
    digest.update(" ".join(extra_flags).encode())
    so_path = os.path.join(build_dir,
                           "lib%s_%s.so" % (name, digest.hexdigest()[:12]))
    header_path = os.path.join(build_dir, "pt_extension.h")
    with open(header_path, "w") as f:
        f.write(_HEADER)
    cmd = ["g++", "-O2", "-std=c++17", "-shared", "-fPIC",
           "-I", build_dir, *extra_flags, *sources, "-o", so_path]
    if verbose:
        print("cpp_extension:", " ".join(cmd))
    proc = subprocess.run(cmd, capture_output=True, text=True)
    if proc.returncode != 0:
        raise InvalidArgumentError(
            "C++ extension %r failed to compile:\n%s" % (name, proc.stderr))
    return so_path


def _make_host_fn(lib, fn_name: str, out_shape: Callable):
    cfn = getattr(lib, fn_name)
    cfn.restype = None

    def host(*arrays) -> np.ndarray:
        arrays = [np.ascontiguousarray(a, np.float32) for a in arrays]
        n = len(arrays)
        ins = (ctypes.POINTER(ctypes.c_float) * n)(*[
            a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))
            for a in arrays])
        shapes_store = [
            (ctypes.c_longlong * max(a.ndim, 1))(*(a.shape or (1,)))
            for a in arrays]
        shapes = (ctypes.POINTER(ctypes.c_longlong) * n)(*shapes_store)
        ndims = (ctypes.c_int * n)(*[a.ndim for a in arrays])
        out = np.zeros(out_shape(*[a.shape for a in arrays]), np.float32)
        cfn(ins, shapes, ndims, ctypes.c_int(n),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)))
        return out

    return host


def load(name: str, sources: Sequence[str],
         functions: Dict[str, dict],
         extra_cxx_cflags: Optional[Sequence[str]] = None,
         build_directory: Optional[str] = None, verbose: bool = False):
    """Compile ``sources`` and return a module-like object exposing each
    function in ``functions`` as a registered framework op.

    functions: ``{op_name: {"out_shape": fn(*in_shapes)->shape,
    "backward": optional python vjp}}`` — out_shape is the InferShapeFn
    (custom_operator.cc parity); the op body runs on host via
    jax.pure_callback, so it composes with jit/TrainStep.
    """
    import jax
    import jax.numpy as jnp

    from ..incubate import register_custom_op

    if not functions:
        raise InvalidArgumentError("load needs a functions={...} mapping")
    build_dir = build_directory or get_build_directory()
    so_path = _compile(name, sources, list(extra_cxx_cflags or ()),
                       build_dir, verbose)
    lib = ctypes.CDLL(so_path)

    class _Module:
        __name__ = name
        _library_path = so_path

    mod = _Module()
    for fn_name, spec in functions.items():
        if "out_shape" not in spec:
            raise InvalidArgumentError(
                "function %r needs an out_shape rule (the InferShapeFn)"
                % fn_name)
        host = _make_host_fn(lib, fn_name, spec["out_shape"])
        out_shape = spec["out_shape"]

        def forward(*arrays, _host=host, _os=out_shape):
            aval = jax.ShapeDtypeStruct(
                tuple(_os(*[tuple(np.shape(a)) for a in arrays])),
                jnp.float32)
            return jax.pure_callback(_host, aval, *arrays, vmap_method=None)

        # re-loading after a source edit must bind the NEW library: registry
        # names are unique, so version the internal name per reload
        base_key = "%s.%s" % (name, fn_name)
        key = base_key
        version = 0
        while True:
            try:
                op = register_custom_op(key, forward,
                                        backward=spec.get("backward"))
                break
            except InvalidArgumentError:
                version += 1
                key = "%s#v%d" % (base_key, version)
        setattr(mod, fn_name, op)
    return mod
