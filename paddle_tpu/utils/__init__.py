"""``paddle_tpu.utils`` (reference: python/paddle/utils/__init__.py —
deprecated, try_import, run_check, require_version, unique_name,
download).  ``run_check`` exercises the real device path (a matmul on the
default backend + an 8-way CPU-mesh psum) instead of the reference's
single/multi-GPU fluid program."""
from __future__ import annotations

import functools
import importlib
import warnings

from . import cpp_extension  # noqa: F401
from . import download  # noqa: F401
from . import unique_name  # noqa: F401

__all__ = ["deprecated", "run_check", "require_version", "try_import",
           "unique_name", "cpp_extension", "download"]


def deprecated(update_to: str = "", since: str = "", reason: str = "",
               level: int = 0):
    """utils/deprecated.py parity: warn (or raise, level=2) on use."""

    def decorator(fn):
        msg = "API %r is deprecated since %s" % (
            getattr(fn, "__name__", str(fn)), since or "this release")
        if update_to:
            msg += ", use %r instead" % update_to
        if reason:
            msg += " (%s)" % reason

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            if level == 2:
                raise RuntimeError(msg)
            if level >= 0:
                warnings.warn(msg, DeprecationWarning, stacklevel=2)
            return fn(*args, **kwargs)

        return wrapper

    return decorator


def try_import(module_name: str, err_msg: str = ""):
    """utils/lazy_import.py parity."""
    try:
        return importlib.import_module(module_name)
    except ImportError:
        raise ImportError(
            err_msg or "%s is required but not installed; this no-egress "
            "build cannot fetch it" % module_name)


def require_version(min_version: str, max_version: str = None) -> bool:
    """fluid/framework.py require_version parity against this package."""
    from ..version import full_version

    def parse(v):
        return tuple(int(x) for x in str(v).split(".")[:3] if x.isdigit())

    cur = parse(full_version)
    if parse(min_version) > cur:
        raise RuntimeError(
            "installed version %s is below required %s"
            % (full_version, min_version))
    if max_version is not None and parse(max_version) < cur:
        raise RuntimeError(
            "installed version %s is above supported %s"
            % (full_version, max_version))
    return True


def run_check() -> None:
    """install_check.py:162 parity: verify the install can compute.

    1) a jitted matmul on the default backend (TPU when attached);
    2) a psum across an 8-device CPU mesh (the collective path).
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    import paddle_tpu as pt

    a = pt.to_tensor(np.ones((2, 2), np.float32))
    out = pt.matmul(a, a)
    assert float(out.value.sum()) == 8.0
    backend = jax.default_backend()

    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    devs = jax.devices()
    n = min(len(devs), 8)
    mesh = Mesh(np.array(devs[:n]), ("dp",))
    x = jax.device_put(jnp.ones((n, 2)), NamedSharding(mesh, P("dp")))
    # eager sum, not jax.jit(lambda ...): an inline jitted lambda would
    # compile fresh on every run_check call (tools/analysis
    # retrace-hazard), and the check only needs the sharded reduction
    total = x.sum()
    assert float(total) == 2 * n
    print("PaddlePaddle-TPU works well on 1 %s device." % backend)
    if n > 1:
        print("PaddlePaddle-TPU works well on %d devices." % n)
    print("PaddlePaddle-TPU is installed successfully!")
