"""Build-integration paths (``paddle.sysconfig``).

Reference: ``python/paddle/sysconfig.py:20-52``. ``get_include`` serves
the C API header (``paddle_tpu_c.h``); ``get_lib`` the directory holding
``libpaddle_tpu_c.so`` (built on demand by ``paddle_tpu.capi.build()``).
"""
from __future__ import annotations

import os

__all__ = ["get_include", "get_lib"]

_PKG = os.path.dirname(os.path.abspath(__file__))


def get_include() -> str:
    """Directory containing the paddle_tpu C/C++ header files."""
    return os.path.join(_PKG, "include")


def get_lib() -> str:
    """Directory containing ``libpaddle_tpu_c.so`` (call
    ``paddle_tpu.capi.build()`` first to compile it)."""
    return os.path.join(_PKG, "capi", "_build")
