"""``paddle_tpu.vision.transforms`` — image preprocessing.

Reference parity: ``python/paddle/vision/transforms/transforms.py`` (class
transforms) + ``functional.py``.  Operates on numpy HWC uint8/float arrays
or PIL Images (host-side preprocessing feeding the DataLoader; device work
starts at ToTensor).
"""
from __future__ import annotations

import numbers
import random
from typing import List, Optional, Sequence, Tuple, Union

import numpy as np

from ...core.errors import InvalidArgumentError
from ...framework.tensor import Tensor

__all__ = [
    "Compose", "ToTensor", "Normalize", "Resize", "CenterCrop", "RandomCrop",
    "RandomHorizontalFlip", "RandomVerticalFlip", "Transpose", "Pad",
    "BrightnessTransform", "ContrastTransform", "SaturationTransform",
    "HueTransform", "ColorJitter", "Grayscale", "RandomRotation",
    "RandomResizedCrop", "to_tensor", "normalize", "resize", "center_crop",
    "crop", "hflip", "vflip", "pad", "adjust_brightness", "adjust_contrast",
    "adjust_saturation", "adjust_hue", "rotate", "to_grayscale",
]


def _to_numpy(img) -> np.ndarray:
    try:
        from PIL import Image

        if isinstance(img, Image.Image):
            return np.asarray(img)
    except ImportError:  # pragma: no cover
        pass
    if isinstance(img, Tensor):
        return np.asarray(img.value)
    return np.asarray(img)


# -- functional (vision/transforms/functional.py parity) --------------------

def to_tensor(pic, data_format: str = "CHW"):
    """HWC uint8 [0,255] → CHW float32 [0,1] Tensor."""
    arr = _to_numpy(pic)
    if arr.ndim == 2:
        arr = arr[:, :, None]
    if arr.dtype == np.uint8:
        arr = arr.astype(np.float32) / 255.0
    else:
        arr = arr.astype(np.float32)
    if data_format == "CHW":
        arr = arr.transpose(2, 0, 1)
    return Tensor(arr, stop_gradient=True)


def normalize(img, mean, std, data_format: str = "CHW", to_rgb: bool = False):
    arr = img.numpy() if isinstance(img, Tensor) else _to_numpy(img).astype(np.float32)
    if to_rgb:  # reference semantics: input is BGR, reverse channels first
        arr = arr[::-1] if data_format == "CHW" else arr[..., ::-1]
    mean = np.asarray(mean, np.float32)
    std = np.asarray(std, np.float32)
    shape = ([-1, 1, 1] if data_format == "CHW" else [1, 1, -1])
    out = (arr - mean.reshape(shape)) / std.reshape(shape)
    return Tensor(out, stop_gradient=True) if isinstance(img, Tensor) else out


def resize(img, size, interpolation: str = "bilinear"):
    arr = _to_numpy(img)
    from PIL import Image

    modes = {"nearest": Image.NEAREST, "bilinear": Image.BILINEAR,
             "bicubic": Image.BICUBIC, "lanczos": Image.LANCZOS}
    if interpolation not in modes:
        raise InvalidArgumentError("unknown interpolation %r" % interpolation)
    h, w = arr.shape[:2]
    if isinstance(size, int):
        if w <= h:
            ow, oh = size, int(size * h / w)
        else:
            oh, ow = size, int(size * w / h)
    else:
        oh, ow = size
    squeeze = arr.ndim == 3 and arr.shape[2] == 1
    pil = Image.fromarray(arr.squeeze(-1) if squeeze else arr)
    out = np.asarray(pil.resize((ow, oh), modes[interpolation]))
    if squeeze:
        out = out[:, :, None]
    return out


def crop(img, top: int, left: int, height: int, width: int):
    arr = _to_numpy(img)
    return arr[top:top + height, left:left + width]


def center_crop(img, output_size):
    arr = _to_numpy(img)
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    h, w = arr.shape[:2]
    th, tw = output_size
    top = int(round((h - th) / 2.0))
    left = int(round((w - tw) / 2.0))
    return crop(arr, top, left, th, tw)


def hflip(img):
    return _to_numpy(img)[:, ::-1].copy()


def vflip(img):
    return _to_numpy(img)[::-1].copy()


def pad(img, padding, fill=0, padding_mode: str = "constant"):
    arr = _to_numpy(img)
    if isinstance(padding, int):
        padding = (padding,) * 4
    elif len(padding) == 2:
        padding = (padding[0], padding[1], padding[0], padding[1])
    left, top, right, bottom = padding
    widths = [(top, bottom), (left, right)] + [(0, 0)] * (arr.ndim - 2)
    if padding_mode == "constant":
        return np.pad(arr, widths, mode="constant", constant_values=fill)
    return np.pad(arr, widths, mode=padding_mode)


# -- class transforms (vision/transforms/transforms.py parity) --------------

class Compose:
    def __init__(self, transforms: Sequence):
        self.transforms = list(transforms)

    def __call__(self, img):
        for t in self.transforms:
            img = t(img)
        return img


class BaseTransform:
    """transforms.py BaseTransform (simplified single-input form)."""

    def __call__(self, img):
        return self._apply_image(img)

    def _apply_image(self, img):
        raise NotImplementedError


class ToTensor(BaseTransform):
    def __init__(self, data_format: str = "CHW", keys=None):
        self.data_format = data_format

    def _apply_image(self, img):
        return to_tensor(img, self.data_format)


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format: str = "CHW",
                 to_rgb: bool = False, keys=None):
        if isinstance(mean, numbers.Number):
            mean = [mean] * 3
        if isinstance(std, numbers.Number):
            std = [std] * 3
        self.mean, self.std = mean, std
        self.data_format = data_format
        self.to_rgb = to_rgb

    def _apply_image(self, img):
        return normalize(img, self.mean, self.std, self.data_format,
                         self.to_rgb)


class Resize(BaseTransform):
    def __init__(self, size, interpolation: str = "bilinear", keys=None):
        self.size = size
        self.interpolation = interpolation

    def _apply_image(self, img):
        return resize(img, self.size, self.interpolation)


class CenterCrop(BaseTransform):
    def __init__(self, size, keys=None):
        self.size = size

    def _apply_image(self, img):
        return center_crop(img, self.size)


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=None, pad_if_needed: bool = False,
                 fill=0, padding_mode: str = "constant", keys=None):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.padding = padding
        self.pad_if_needed = pad_if_needed
        self.fill = fill
        self.padding_mode = padding_mode

    def _apply_image(self, img):
        arr = _to_numpy(img)
        if self.padding is not None:
            arr = pad(arr, self.padding, self.fill, self.padding_mode)
        th, tw = self.size
        h, w = arr.shape[:2]
        if self.pad_if_needed and (h < th or w < tw):
            arr = pad(arr, (max(0, tw - w), max(0, th - h)), self.fill,
                      self.padding_mode)
            h, w = arr.shape[:2]
        if h == th and w == tw:
            return arr
        top = random.randint(0, h - th)
        left = random.randint(0, w - tw)
        return crop(arr, top, left, th, tw)


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob: float = 0.5, keys=None):
        self.prob = prob

    def _apply_image(self, img):
        if random.random() < self.prob:
            return hflip(img)
        return _to_numpy(img)


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob: float = 0.5, keys=None):
        self.prob = prob

    def _apply_image(self, img):
        if random.random() < self.prob:
            return vflip(img)
        return _to_numpy(img)


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1), keys=None):
        self.order = tuple(order)

    def _apply_image(self, img):
        arr = _to_numpy(img)
        if arr.ndim == 2:
            arr = arr[:, :, None]
        return arr.transpose(self.order)


class Pad(BaseTransform):
    def __init__(self, padding, fill=0, padding_mode: str = "constant", keys=None):
        self.padding = padding
        self.fill = fill
        self.padding_mode = padding_mode

    def _apply_image(self, img):
        return pad(img, self.padding, self.fill, self.padding_mode)


class BrightnessTransform(BaseTransform):
    def __init__(self, value: float, keys=None):
        if value < 0:
            raise InvalidArgumentError("brightness value must be non-negative")
        self.value = float(value)

    def _apply_image(self, img):
        arr = _to_numpy(img)
        if self.value == 0:
            return arr
        factor = random.uniform(max(0.0, 1 - self.value), 1 + self.value)
        if arr.dtype == np.uint8:
            return np.clip(arr.astype(np.float32) * factor, 0, 255).astype(np.uint8)
        return (arr * np.asarray(factor, arr.dtype))  # float stays float


# -- photometric functional ops (transforms/functional.py parity) ----------

def _as_float(arr):
    was_uint8 = arr.dtype == np.uint8
    return arr.astype(np.float32), was_uint8


def _restore(arr, was_uint8):
    if was_uint8:
        return np.clip(arr, 0, 255).astype(np.uint8)
    return arr.astype(np.float32)


def adjust_brightness(img, brightness_factor: float):
    arr, u8 = _as_float(_to_numpy(img))
    return _restore(arr * brightness_factor, u8)


def to_grayscale(img, num_output_channels: int = 1):
    arr = _to_numpy(img)
    gray = (arr[..., 0] * 0.299 + arr[..., 1] * 0.587 + arr[..., 2] * 0.114)
    gray = gray[..., None]
    if num_output_channels == 3:
        gray = np.repeat(gray, 3, axis=-1)
    return gray.astype(arr.dtype)


def adjust_contrast(img, contrast_factor: float):
    arr, u8 = _as_float(_to_numpy(img))
    mean = to_grayscale(arr).mean()
    return _restore(arr * contrast_factor + mean * (1 - contrast_factor), u8)


def adjust_saturation(img, saturation_factor: float):
    arr, u8 = _as_float(_to_numpy(img))
    gray = to_grayscale(arr)
    return _restore(arr * saturation_factor
                    + gray * (1 - saturation_factor), u8)


def adjust_hue(img, hue_factor: float):
    """Shift hue by hue_factor (in [-0.5, 0.5]) via HSV round-trip."""
    if not -0.5 <= hue_factor <= 0.5:
        raise InvalidArgumentError(
            "hue_factor must be in [-0.5, 0.5], got %s" % hue_factor)
    arr = _to_numpy(img)
    f, u8 = _as_float(arr)
    f = f / 255.0 if u8 else f
    r, g, b = f[..., 0], f[..., 1], f[..., 2]
    maxc = f[..., :3].max(-1)
    minc = f[..., :3].min(-1)
    v = maxc
    span = maxc - minc
    s = np.where(maxc > 0, span / np.maximum(maxc, 1e-12), 0.0)
    safe = np.maximum(span, 1e-12)
    rc = (maxc - r) / safe
    gc = (maxc - g) / safe
    bc = (maxc - b) / safe
    h = np.where(maxc == r, bc - gc,
                 np.where(maxc == g, 2.0 + rc - bc, 4.0 + gc - rc))
    h = np.where(span > 0, (h / 6.0) % 1.0, 0.0)
    h = (h + hue_factor) % 1.0
    # hsv -> rgb
    i = np.floor(h * 6.0)
    fr = h * 6.0 - i
    p = v * (1.0 - s)
    q = v * (1.0 - s * fr)
    t = v * (1.0 - s * (1.0 - fr))
    i = (i.astype(np.int32) % 6)[..., None]
    out = np.select(
        [i == 0, i == 1, i == 2, i == 3, i == 4, i == 5],
        [np.stack([v, t, p], -1), np.stack([q, v, p], -1),
         np.stack([p, v, t], -1), np.stack([p, q, v], -1),
         np.stack([t, p, v], -1), np.stack([v, p, q], -1)])
    return _restore(out * 255.0 if u8 else out, u8)


def rotate(img, angle: float, interpolation: str = "nearest",
           expand: bool = False, center=None, fill=0):
    """Rotate counter-clockwise by angle degrees (inverse affine map)."""
    arr = _to_numpy(img)
    was_2d = arr.ndim == 2
    if was_2d:
        arr = arr[:, :, None]
    H, W = arr.shape[:2]
    rad = np.deg2rad(angle)
    cos, sin = np.cos(rad), np.sin(rad)
    cy, cx = ((H - 1) / 2.0, (W - 1) / 2.0) if center is None \
        else (center[1], center[0])
    if expand:
        newW = int(np.ceil(abs(W * cos) + abs(H * sin)))
        newH = int(np.ceil(abs(W * sin) + abs(H * cos)))
    else:
        newW, newH = W, H
    ys, xs = np.meshgrid(np.arange(newH), np.arange(newW), indexing="ij")
    # destination center
    dy, dx = (newH - 1) / 2.0, (newW - 1) / 2.0
    yy = ys - (dy if expand else cy)
    xx = xs - (dx if expand else cx)
    # inverse rotation back into source coords
    sx = cos * xx - sin * yy + cx
    sy = sin * xx + cos * yy + cy
    if interpolation == "bilinear":
        x0 = np.floor(sx).astype(np.int64)
        y0 = np.floor(sy).astype(np.int64)
        wx = (sx - x0)[..., None]
        wy = (sy - y0)[..., None]

        def take(yi, xi):
            inside = (yi >= 0) & (yi < H) & (xi >= 0) & (xi < W)
            v = arr[np.clip(yi, 0, H - 1), np.clip(xi, 0, W - 1)].astype(
                np.float32)
            v[~inside] = fill
            return v

        out = (take(y0, x0) * (1 - wy) * (1 - wx)
               + take(y0, x0 + 1) * (1 - wy) * wx
               + take(y0 + 1, x0) * wy * (1 - wx)
               + take(y0 + 1, x0 + 1) * wy * wx)
        out = out.astype(arr.dtype) if arr.dtype != np.uint8 \
            else np.clip(out, 0, 255).astype(np.uint8)
    else:
        xi = np.round(sx).astype(np.int64)
        yi = np.round(sy).astype(np.int64)
        inside = (yi >= 0) & (yi < H) & (xi >= 0) & (xi < W)
        out = arr[np.clip(yi, 0, H - 1), np.clip(xi, 0, W - 1)].copy()
        out[~inside] = fill
    return out[:, :, 0] if was_2d else out


# -- photometric / geometric transform classes ------------------------------

class ContrastTransform(BaseTransform):
    def __init__(self, value: float, keys=None):
        if value < 0:
            raise InvalidArgumentError("contrast value must be non-negative")
        self.value = float(value)

    def _apply_image(self, img):
        if self.value == 0:
            return _to_numpy(img)
        factor = random.uniform(max(0.0, 1 - self.value), 1 + self.value)
        return adjust_contrast(img, factor)


class SaturationTransform(BaseTransform):
    def __init__(self, value: float, keys=None):
        if value < 0:
            raise InvalidArgumentError(
                "saturation value must be non-negative")
        self.value = float(value)

    def _apply_image(self, img):
        if self.value == 0:
            return _to_numpy(img)
        factor = random.uniform(max(0.0, 1 - self.value), 1 + self.value)
        return adjust_saturation(img, factor)


class HueTransform(BaseTransform):
    def __init__(self, value: float, keys=None):
        if not 0 <= value <= 0.5:
            raise InvalidArgumentError("hue value must be in [0, 0.5]")
        self.value = float(value)

    def _apply_image(self, img):
        if self.value == 0:
            return _to_numpy(img)
        return adjust_hue(img, random.uniform(-self.value, self.value))


class ColorJitter(BaseTransform):
    """Random brightness/contrast/saturation/hue in random order."""

    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0,
                 keys=None):
        self._transforms = [
            BrightnessTransform(brightness), ContrastTransform(contrast),
            SaturationTransform(saturation), HueTransform(hue),
        ]

    def _apply_image(self, img):
        order = list(range(4))
        random.shuffle(order)
        for i in order:
            img = self._transforms[i]._apply_image(img)
        return img


class Grayscale(BaseTransform):
    def __init__(self, num_output_channels: int = 1, keys=None):
        self.num_output_channels = num_output_channels

    def _apply_image(self, img):
        return to_grayscale(img, self.num_output_channels)


class RandomRotation(BaseTransform):
    def __init__(self, degrees, interpolation: str = "nearest",
                 expand: bool = False, center=None, fill=0, keys=None):
        if isinstance(degrees, numbers.Number):
            if degrees < 0:
                raise InvalidArgumentError(
                    "degrees must be non-negative when scalar")
            self.degrees = (-float(degrees), float(degrees))
        else:
            self.degrees = (float(degrees[0]), float(degrees[1]))
        self.interpolation = interpolation
        self.expand = expand
        self.center = center
        self.fill = fill

    def _apply_image(self, img):
        angle = random.uniform(*self.degrees)
        return rotate(img, angle, self.interpolation, self.expand,
                      self.center, self.fill)


class RandomResizedCrop(BaseTransform):
    """Random area/aspect crop resized to a fixed size (inception-style)."""

    def __init__(self, size, scale=(0.08, 1.0), ratio=(3.0 / 4, 4.0 / 3),
                 interpolation: str = "bilinear", keys=None):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.scale = scale
        self.ratio = ratio
        self.interpolation = interpolation

    def _sample(self, H, W):
        area = H * W
        for _ in range(10):
            target = area * random.uniform(*self.scale)
            log_ratio = (np.log(self.ratio[0]), np.log(self.ratio[1]))
            aspect = np.exp(random.uniform(*log_ratio))
            w = int(round(np.sqrt(target * aspect)))
            h = int(round(np.sqrt(target / aspect)))
            if 0 < w <= W and 0 < h <= H:
                i = random.randint(0, H - h)
                j = random.randint(0, W - w)
                return i, j, h, w
        # fallback: center crop at the closest valid aspect
        in_ratio = W / H
        if in_ratio < self.ratio[0]:
            w, h = W, int(round(W / self.ratio[0]))
        elif in_ratio > self.ratio[1]:
            h, w = H, int(round(H * self.ratio[1]))
        else:
            w, h = W, H
        return (H - h) // 2, (W - w) // 2, h, w

    def _apply_image(self, img):
        arr = _to_numpy(img)
        i, j, h, w = self._sample(arr.shape[0], arr.shape[1])
        return resize(arr[i:i + h, j:j + w], self.size, self.interpolation)
