"""``paddle_tpu.vision.transforms`` — image preprocessing.

Reference parity: ``python/paddle/vision/transforms/transforms.py`` (class
transforms) + ``functional.py``.  Operates on numpy HWC uint8/float arrays
or PIL Images (host-side preprocessing feeding the DataLoader; device work
starts at ToTensor).
"""
from __future__ import annotations

import numbers
import random
from typing import List, Optional, Sequence, Tuple, Union

import numpy as np

from ...core.errors import InvalidArgumentError
from ...framework.tensor import Tensor

__all__ = [
    "Compose", "ToTensor", "Normalize", "Resize", "CenterCrop", "RandomCrop",
    "RandomHorizontalFlip", "RandomVerticalFlip", "Transpose", "Pad",
    "BrightnessTransform", "to_tensor", "normalize", "resize", "center_crop",
    "crop", "hflip", "vflip", "pad",
]


def _to_numpy(img) -> np.ndarray:
    try:
        from PIL import Image

        if isinstance(img, Image.Image):
            return np.asarray(img)
    except ImportError:  # pragma: no cover
        pass
    if isinstance(img, Tensor):
        return np.asarray(img.value)
    return np.asarray(img)


# -- functional (vision/transforms/functional.py parity) --------------------

def to_tensor(pic, data_format: str = "CHW"):
    """HWC uint8 [0,255] → CHW float32 [0,1] Tensor."""
    arr = _to_numpy(pic)
    if arr.ndim == 2:
        arr = arr[:, :, None]
    if arr.dtype == np.uint8:
        arr = arr.astype(np.float32) / 255.0
    else:
        arr = arr.astype(np.float32)
    if data_format == "CHW":
        arr = arr.transpose(2, 0, 1)
    return Tensor(arr, stop_gradient=True)


def normalize(img, mean, std, data_format: str = "CHW", to_rgb: bool = False):
    arr = img.numpy() if isinstance(img, Tensor) else _to_numpy(img).astype(np.float32)
    if to_rgb:  # reference semantics: input is BGR, reverse channels first
        arr = arr[::-1] if data_format == "CHW" else arr[..., ::-1]
    mean = np.asarray(mean, np.float32)
    std = np.asarray(std, np.float32)
    shape = ([-1, 1, 1] if data_format == "CHW" else [1, 1, -1])
    out = (arr - mean.reshape(shape)) / std.reshape(shape)
    return Tensor(out, stop_gradient=True) if isinstance(img, Tensor) else out


def resize(img, size, interpolation: str = "bilinear"):
    arr = _to_numpy(img)
    from PIL import Image

    modes = {"nearest": Image.NEAREST, "bilinear": Image.BILINEAR,
             "bicubic": Image.BICUBIC, "lanczos": Image.LANCZOS}
    if interpolation not in modes:
        raise InvalidArgumentError("unknown interpolation %r" % interpolation)
    h, w = arr.shape[:2]
    if isinstance(size, int):
        if w <= h:
            ow, oh = size, int(size * h / w)
        else:
            oh, ow = size, int(size * w / h)
    else:
        oh, ow = size
    squeeze = arr.ndim == 3 and arr.shape[2] == 1
    pil = Image.fromarray(arr.squeeze(-1) if squeeze else arr)
    out = np.asarray(pil.resize((ow, oh), modes[interpolation]))
    if squeeze:
        out = out[:, :, None]
    return out


def crop(img, top: int, left: int, height: int, width: int):
    arr = _to_numpy(img)
    return arr[top:top + height, left:left + width]


def center_crop(img, output_size):
    arr = _to_numpy(img)
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    h, w = arr.shape[:2]
    th, tw = output_size
    top = int(round((h - th) / 2.0))
    left = int(round((w - tw) / 2.0))
    return crop(arr, top, left, th, tw)


def hflip(img):
    return _to_numpy(img)[:, ::-1].copy()


def vflip(img):
    return _to_numpy(img)[::-1].copy()


def pad(img, padding, fill=0, padding_mode: str = "constant"):
    arr = _to_numpy(img)
    if isinstance(padding, int):
        padding = (padding,) * 4
    elif len(padding) == 2:
        padding = (padding[0], padding[1], padding[0], padding[1])
    left, top, right, bottom = padding
    widths = [(top, bottom), (left, right)] + [(0, 0)] * (arr.ndim - 2)
    if padding_mode == "constant":
        return np.pad(arr, widths, mode="constant", constant_values=fill)
    return np.pad(arr, widths, mode=padding_mode)


# -- class transforms (vision/transforms/transforms.py parity) --------------

class Compose:
    def __init__(self, transforms: Sequence):
        self.transforms = list(transforms)

    def __call__(self, img):
        for t in self.transforms:
            img = t(img)
        return img


class BaseTransform:
    """transforms.py BaseTransform (simplified single-input form)."""

    def __call__(self, img):
        return self._apply_image(img)

    def _apply_image(self, img):
        raise NotImplementedError


class ToTensor(BaseTransform):
    def __init__(self, data_format: str = "CHW", keys=None):
        self.data_format = data_format

    def _apply_image(self, img):
        return to_tensor(img, self.data_format)


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format: str = "CHW",
                 to_rgb: bool = False, keys=None):
        if isinstance(mean, numbers.Number):
            mean = [mean] * 3
        if isinstance(std, numbers.Number):
            std = [std] * 3
        self.mean, self.std = mean, std
        self.data_format = data_format
        self.to_rgb = to_rgb

    def _apply_image(self, img):
        return normalize(img, self.mean, self.std, self.data_format,
                         self.to_rgb)


class Resize(BaseTransform):
    def __init__(self, size, interpolation: str = "bilinear", keys=None):
        self.size = size
        self.interpolation = interpolation

    def _apply_image(self, img):
        return resize(img, self.size, self.interpolation)


class CenterCrop(BaseTransform):
    def __init__(self, size, keys=None):
        self.size = size

    def _apply_image(self, img):
        return center_crop(img, self.size)


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=None, pad_if_needed: bool = False,
                 fill=0, padding_mode: str = "constant", keys=None):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.padding = padding
        self.pad_if_needed = pad_if_needed
        self.fill = fill
        self.padding_mode = padding_mode

    def _apply_image(self, img):
        arr = _to_numpy(img)
        if self.padding is not None:
            arr = pad(arr, self.padding, self.fill, self.padding_mode)
        th, tw = self.size
        h, w = arr.shape[:2]
        if self.pad_if_needed and (h < th or w < tw):
            arr = pad(arr, (max(0, tw - w), max(0, th - h)), self.fill,
                      self.padding_mode)
            h, w = arr.shape[:2]
        if h == th and w == tw:
            return arr
        top = random.randint(0, h - th)
        left = random.randint(0, w - tw)
        return crop(arr, top, left, th, tw)


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob: float = 0.5, keys=None):
        self.prob = prob

    def _apply_image(self, img):
        if random.random() < self.prob:
            return hflip(img)
        return _to_numpy(img)


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob: float = 0.5, keys=None):
        self.prob = prob

    def _apply_image(self, img):
        if random.random() < self.prob:
            return vflip(img)
        return _to_numpy(img)


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1), keys=None):
        self.order = tuple(order)

    def _apply_image(self, img):
        arr = _to_numpy(img)
        if arr.ndim == 2:
            arr = arr[:, :, None]
        return arr.transpose(self.order)


class Pad(BaseTransform):
    def __init__(self, padding, fill=0, padding_mode: str = "constant", keys=None):
        self.padding = padding
        self.fill = fill
        self.padding_mode = padding_mode

    def _apply_image(self, img):
        return pad(img, self.padding, self.fill, self.padding_mode)


class BrightnessTransform(BaseTransform):
    def __init__(self, value: float, keys=None):
        if value < 0:
            raise InvalidArgumentError("brightness value must be non-negative")
        self.value = float(value)

    def _apply_image(self, img):
        arr = _to_numpy(img)
        if self.value == 0:
            return arr
        factor = random.uniform(max(0.0, 1 - self.value), 1 + self.value)
        if arr.dtype == np.uint8:
            return np.clip(arr.astype(np.float32) * factor, 0, 255).astype(np.uint8)
        return (arr * np.asarray(factor, arr.dtype))  # float stays float
