"""``paddle_tpu.vision`` — models, transforms, datasets.

Reference parity: ``python/paddle/vision/``.
"""
from . import datasets  # noqa: F401
from . import models  # noqa: F401
from . import ops  # noqa: F401
from . import transforms  # noqa: F401

__all__ = ["datasets", "models", "ops", "transforms"]


_image_backend = "cv2"


def set_image_backend(backend: str) -> None:
    """image.py parity: select the decode backend ('pil'/'cv2'-style numpy)."""
    from ..core.errors import InvalidArgumentError

    if backend not in ("pil", "cv2"):
        raise InvalidArgumentError(
            "image backend must be 'pil' or 'cv2', got %r" % backend)
    global _image_backend
    _image_backend = backend


def get_image_backend() -> str:
    return _image_backend


def image_load(path: str, backend=None):
    """image.py parity: load an image file; numpy HWC for 'cv2' mode, a PIL
    handle for 'pil'."""
    from PIL import Image

    img = Image.open(path)
    if (backend or _image_backend) == "pil":
        return img
    import numpy as np

    return np.asarray(img)


__all__ += ["set_image_backend", "get_image_backend", "image_load"]
