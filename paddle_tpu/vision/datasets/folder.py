"""Directory-tree datasets (reference: python/paddle/vision/datasets/folder.py
— ``DatasetFolder:65``, ``ImageFolder:222``).

Images decode to HWC uint8 numpy arrays (the transforms' native layout)
rather than PIL handles: downstream is a jnp pipeline, not torchvision.
``.npy`` files are accepted alongside the standard image extensions so
synthetic datasets can be laid out without an image codec.
"""
from __future__ import annotations

import os
from typing import Callable, List, Optional, Sequence, Tuple

import numpy as np

from ...core.errors import InvalidArgumentError
from ...io import Dataset

__all__ = ["DatasetFolder", "ImageFolder", "has_valid_extension",
           "make_dataset", "default_loader"]

IMG_EXTENSIONS = (".jpg", ".jpeg", ".png", ".ppm", ".bmp", ".pgm", ".tif",
                  ".tiff", ".webp", ".npy")


def has_valid_extension(filename: str, extensions: Sequence[str]) -> bool:
    """folder.py:26 parity."""
    if not isinstance(extensions, (list, tuple)):
        raise InvalidArgumentError("`extensions` must be list or tuple")
    return filename.lower().endswith(tuple(x.lower() for x in extensions))


def default_loader(path: str) -> np.ndarray:
    """Decode one sample file to an HWC uint8 array (npy passes through)."""
    if path.lower().endswith(".npy"):
        return np.load(path)
    from PIL import Image

    with Image.open(path) as img:
        return np.asarray(img.convert("RGB"))


def make_dataset(directory: str, class_to_idx: dict, extensions=None,
                 is_valid_file: Optional[Callable] = None
                 ) -> List[Tuple[str, int]]:
    """folder.py:42 parity: walk class subdirs, collect (path, class_idx)."""
    directory = os.path.expanduser(directory)
    if (extensions is None) == (is_valid_file is None):
        raise InvalidArgumentError(
            "pass exactly one of extensions= / is_valid_file=")
    if extensions is not None:
        def is_valid_file(x):
            return has_valid_extension(x, extensions)
    samples = []
    for target in sorted(class_to_idx):
        d = os.path.join(directory, target)
        if not os.path.isdir(d):
            continue
        for root, _, fnames in sorted(os.walk(d, followlinks=True)):
            for fname in sorted(fnames):
                path = os.path.join(root, fname)
                if is_valid_file(path):
                    samples.append((path, class_to_idx[target]))
    return samples


class DatasetFolder(Dataset):
    """folder.py:65 parity: root/class_x/sample.ext layout → (img, label)."""

    def __init__(self, root: str, loader: Optional[Callable] = None,
                 extensions=None, transform: Optional[Callable] = None,
                 is_valid_file: Optional[Callable] = None):
        self.root = root
        self.transform = transform
        self.loader = loader or default_loader
        if extensions is None and is_valid_file is None:
            extensions = IMG_EXTENSIONS
        classes, class_to_idx = self._find_classes(root)
        samples = make_dataset(root, class_to_idx, extensions, is_valid_file)
        if not samples:
            raise InvalidArgumentError(
                "found 0 files in subfolders of %s (extensions: %s)"
                % (root, extensions))
        self.classes = classes
        self.class_to_idx = class_to_idx
        self.samples = samples
        self.targets = [s[1] for s in samples]

    @staticmethod
    def _find_classes(directory: str):
        classes = sorted(e.name for e in os.scandir(directory) if e.is_dir())
        if not classes:
            raise InvalidArgumentError(
                "no class subdirectories under %s" % directory)
        return classes, {c: i for i, c in enumerate(classes)}

    def __getitem__(self, index: int):
        path, target = self.samples[index]
        sample = self.loader(path)
        if self.transform is not None:
            sample = self.transform(sample)
        return sample, target

    def __len__(self):
        return len(self.samples)


class ImageFolder(Dataset):
    """folder.py:222 parity: flat (recursive) image list → [img]."""

    def __init__(self, root: str, loader: Optional[Callable] = None,
                 extensions=None, transform: Optional[Callable] = None,
                 is_valid_file: Optional[Callable] = None):
        self.root = root
        self.transform = transform
        self.loader = loader or default_loader
        if extensions is None and is_valid_file is None:
            extensions = IMG_EXTENSIONS
        if extensions is not None and is_valid_file is None:
            def is_valid_file(x):
                return has_valid_extension(x, extensions)
        samples = []
        for r, _, fnames in sorted(os.walk(root, followlinks=True)):
            for fname in sorted(fnames):
                path = os.path.join(r, fname)
                if is_valid_file(path):
                    samples.append(path)
        if not samples:
            raise InvalidArgumentError("found 0 files under %s" % root)
        self.samples = samples

    def __getitem__(self, index: int):
        sample = self.loader(self.samples[index])
        if self.transform is not None:
            sample = self.transform(sample)
        return [sample]

    def __len__(self):
        return len(self.samples)
