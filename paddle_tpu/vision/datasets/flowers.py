"""Flowers-102 dataset (reference: python/paddle/vision/datasets/flowers.py).

Reads images straight out of the tgz member stream instead of extracting
the archive to disk (the reference unpacks 330MB next to the tarball);
labels/split indices come from the standard scipy ``.mat`` files.
"""
from __future__ import annotations

import io
import tarfile
from typing import Callable, Optional

import numpy as np

from ...core.errors import InvalidArgumentError
from ...io import Dataset

__all__ = ["Flowers"]

# reference flowers.py:37: tstid is the (larger) train split's flag upstream
MODE_FLAG_MAP = {"train": "tstid", "test": "trnid", "valid": "valid"}


class Flowers(Dataset):
    """flowers.py:77 parity: (image HWC uint8, label int64[1]) pairs."""

    def __init__(self, data_file: Optional[str] = None,
                 label_file: Optional[str] = None,
                 setid_file: Optional[str] = None, mode: str = "train",
                 transform: Optional[Callable] = None,
                 download: bool = False, backend: str = "cv2"):
        if mode.lower() not in MODE_FLAG_MAP:
            raise InvalidArgumentError(
                "mode must be one of %s, got %r"
                % (sorted(MODE_FLAG_MAP), mode))
        if not (data_file and label_file and setid_file):
            raise InvalidArgumentError(
                "Flowers needs data_file=, label_file= and setid_file= "
                "(no-egress build: download=True unavailable)")
        self.transform = transform
        self.mode = mode.lower()

        import scipy.io as scio

        self.labels = scio.loadmat(label_file)["labels"][0]
        self.indexes = scio.loadmat(setid_file)[MODE_FLAG_MAP[self.mode]][0]
        self._data_file = data_file
        self._tar_cache = None  # (pid, TarFile, members) — see _archive
        with tarfile.open(data_file) as tar:
            self._names = set(m.name for m in tar.getmembers())

    def _archive(self):
        """Per-process tar handle: forked DataLoader workers must not share
        one file descriptor's offset (reads would interleave)."""
        import os

        pid = os.getpid()
        if self._tar_cache is None or self._tar_cache[0] != pid:
            tar = tarfile.open(self._data_file)
            self._tar_cache = (pid, tar, {m.name: m for m in tar.getmembers()})
        return self._tar_cache[1], self._tar_cache[2]

    def __getitem__(self, idx: int):
        index = int(self.indexes[idx])
        label = np.array([self.labels[index - 1]], dtype="int64")
        name = "jpg/image_%05d.jpg" % index
        if name not in self._names:
            raise InvalidArgumentError(
                "member %s missing from flowers archive" % name)
        from PIL import Image

        tar, members = self._archive()
        raw = tar.extractfile(members[name]).read()
        image = np.asarray(Image.open(io.BytesIO(raw)).convert("RGB"))
        if self.transform is not None:
            image = self.transform(image)
        return image, label

    def __len__(self):
        return len(self.indexes)
