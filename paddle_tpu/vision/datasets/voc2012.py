"""VOC2012 segmentation dataset (reference:
python/paddle/vision/datasets/voc2012.py).

Streams (image, segmentation-mask) pairs from the VOCtrainval tar without
extracting it; masks keep their palette indices as uint8 class ids.
"""
from __future__ import annotations

import io
import tarfile
from typing import Callable, Optional

import numpy as np

from ...core.errors import InvalidArgumentError
from ...io import Dataset

__all__ = ["VOC2012"]

SET_FILE = "VOCdevkit/VOC2012/ImageSets/Segmentation/{}.txt"
DATA_FILE = "VOCdevkit/VOC2012/JPEGImages/{}.jpg"
LABEL_FILE = "VOCdevkit/VOC2012/SegmentationClass/{}.png"
# Reference voc2012.py:85 maps train->trainval (2913 imgs), test->train,
# valid->val; matching it exactly so ported code sees the same splits.
MODE_FLAG_MAP = {"train": "trainval", "test": "train", "valid": "val"}


class VOC2012(Dataset):
    """voc2012.py:89 parity: (image HWC uint8, mask HW uint8) pairs."""

    def __init__(self, data_file: Optional[str] = None, mode: str = "train",
                 transform: Optional[Callable] = None,
                 download: bool = False, backend: str = "cv2"):
        if mode.lower() not in MODE_FLAG_MAP:
            raise InvalidArgumentError(
                "mode must be one of %s, got %r"
                % (sorted(MODE_FLAG_MAP), mode))
        if not data_file:
            raise InvalidArgumentError(
                "VOC2012 needs data_file= (no-egress build: download=True "
                "unavailable)")
        self.transform = transform
        self.flag = MODE_FLAG_MAP[mode.lower()]
        self._data_file = data_file
        self._tar_cache = None  # (pid, TarFile, members) — see _archive
        set_name = SET_FILE.format(self.flag)
        with tarfile.open(data_file) as tar:
            members = {m.name: m for m in tar.getmembers()}
            if set_name not in members:
                raise InvalidArgumentError(
                    "split file %s missing from archive" % set_name)
            names = tar.extractfile(members[set_name]).read()
        self.data = []
        self.labels = []
        for line in names.decode("utf-8").splitlines():
            line = line.strip()
            if line:
                self.data.append(DATA_FILE.format(line))
                self.labels.append(LABEL_FILE.format(line))

    def _archive(self):
        """Per-process tar handle: forked DataLoader workers must not share
        one file descriptor's offset (reads would interleave)."""
        import os

        pid = os.getpid()
        if self._tar_cache is None or self._tar_cache[0] != pid:
            tar = tarfile.open(self._data_file)
            self._tar_cache = (pid, tar, {m.name: m for m in tar.getmembers()})
        return self._tar_cache[1], self._tar_cache[2]

    def _read_image(self, name: str, mode: Optional[str] = None):
        from PIL import Image

        tar, members = self._archive()
        raw = tar.extractfile(members[name]).read()
        img = Image.open(io.BytesIO(raw))
        if mode is not None:
            img = img.convert(mode)
        return np.asarray(img)

    def __getitem__(self, idx: int):
        image = self._read_image(self.data[idx], "RGB")
        label = self._read_image(self.labels[idx])  # palette ids as classes
        if self.transform is not None:
            image = self.transform(image)
        return image, label

    def __len__(self):
        return len(self.data)
