"""``paddle_tpu.vision.datasets`` — standard vision datasets.

Reference parity: ``python/paddle/vision/datasets/`` (mnist.py, cifar.py).
This build has no network egress, so ``download=True`` raises with
instructions; local files parse with the standard formats (IDX for MNIST,
the python-pickle batches for CIFAR).
"""
from __future__ import annotations

import gzip
import os
import pickle
import tarfile
from typing import Callable, Optional

import numpy as np

from ...core.errors import InvalidArgumentError
from ...io import Dataset

__all__ = [
    "MNIST", "FashionMNIST", "Cifar10", "Cifar100", "Flowers", "VOC2012",
    "DatasetFolder", "ImageFolder",
]


def _no_download(name: str):
    raise InvalidArgumentError(
        "%s: download=True is unavailable in this no-egress build; place the "
        "standard files locally and pass image_path/label_path (MNIST) or "
        "data_file (CIFAR)" % name)


def _read_idx_images(path: str) -> np.ndarray:
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        data = f.read()
    magic = int.from_bytes(data[0:4], "big")
    if magic != 2051:
        raise InvalidArgumentError("bad IDX image magic %d in %s" % (magic, path))
    n = int.from_bytes(data[4:8], "big")
    rows = int.from_bytes(data[8:12], "big")
    cols = int.from_bytes(data[12:16], "big")
    return np.frombuffer(data, np.uint8, offset=16).reshape(n, rows, cols)


def _read_idx_labels(path: str) -> np.ndarray:
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        data = f.read()
    magic = int.from_bytes(data[0:4], "big")
    if magic != 2049:
        raise InvalidArgumentError("bad IDX label magic %d in %s" % (magic, path))
    return np.frombuffer(data, np.uint8, offset=8)


class MNIST(Dataset):
    """vision/datasets/mnist.py parity (IDX file format)."""

    NAME = "MNIST"

    def __init__(self, image_path: Optional[str] = None,
                 label_path: Optional[str] = None, mode: str = "train",
                 transform: Optional[Callable] = None, download: bool = False,
                 backend: str = "cv2"):
        if image_path is None or label_path is None:
            if download:
                _no_download(self.NAME)
            raise InvalidArgumentError(
                "%s needs image_path= and label_path= (no-egress build)"
                % self.NAME)
        self.mode = mode
        self.transform = transform
        self.images = _read_idx_images(image_path)
        self.labels = _read_idx_labels(label_path)
        if len(self.images) != len(self.labels):
            raise InvalidArgumentError(
                "image/label count mismatch: %d vs %d"
                % (len(self.images), len(self.labels)))

    def __len__(self):
        return len(self.images)

    def __getitem__(self, idx):
        img = self.images[idx]
        label = np.asarray([self.labels[idx]], np.int64)
        if self.transform is not None:
            img = self.transform(img)
        return img, label


class FashionMNIST(MNIST):
    NAME = "FashionMNIST"


class _CifarBase(Dataset):
    """vision/datasets/cifar.py parity (tar.gz of pickle batches)."""

    NAME = "Cifar"
    _train_members: tuple = ()
    _test_members: tuple = ()
    _label_key = b"labels"

    def __init__(self, data_file: Optional[str] = None, mode: str = "train",
                 transform: Optional[Callable] = None, download: bool = False,
                 backend: str = "cv2"):
        if data_file is None:
            if download:
                _no_download(self.NAME)
            raise InvalidArgumentError(
                "%s needs data_file= (no-egress build)" % self.NAME)
        if mode not in ("train", "test"):
            raise InvalidArgumentError(
                "%s mode must be 'train' or 'test', got %r" % (self.NAME, mode))
        self.mode = mode
        self.transform = transform
        members = self._train_members if mode == "train" else self._test_members
        images, labels = [], []
        with tarfile.open(data_file, "r:*") as tar:
            names = {os.path.basename(m.name): m for m in tar.getmembers()}
            for want in members:
                if want not in names:
                    raise InvalidArgumentError(
                        "%s member %r missing from %s" % (self.NAME, want, data_file))
                batch = pickle.loads(tar.extractfile(names[want]).read(),
                                     encoding="bytes")
                images.append(np.asarray(batch[b"data"], np.uint8))
                labels.extend(batch[self._label_key])
        self.data = np.concatenate(images).reshape(-1, 3, 32, 32)
        self.labels = np.asarray(labels, np.int64)

    def __len__(self):
        return len(self.data)

    def __getitem__(self, idx):
        img = self.data[idx].transpose(1, 2, 0)  # HWC for transforms
        label = np.asarray([self.labels[idx]], np.int64)
        if self.transform is not None:
            img = self.transform(img)
        return img, label


class Cifar10(_CifarBase):
    NAME = "Cifar10"
    _train_members = tuple("data_batch_%d" % i for i in range(1, 6))
    _test_members = ("test_batch",)
    _label_key = b"labels"


class Cifar100(_CifarBase):
    NAME = "Cifar100"
    _train_members = ("train",)
    _test_members = ("test",)
    _label_key = b"fine_labels"


from .flowers import Flowers  # noqa: E402,F401
from .folder import DatasetFolder, ImageFolder  # noqa: E402,F401
from .voc2012 import VOC2012  # noqa: E402,F401
