"""MobileNetV3 Small/Large.

The mounted reference snapshot's zoo carries lenet/mobilenet(v1/v2)/resnet/
vgg; V3 is part of the upstream paddle.vision surface the framework targets
— architecture per Howard et al. 2019 (SE blocks, hardswish), API in the
paddle zoo style."""
from __future__ import annotations

from ... import nn
from .mobilenetv2 import _make_divisible

__all__ = ["MobileNetV3Small", "MobileNetV3Large",
           "mobilenet_v3_small", "mobilenet_v3_large"]


class _SqueezeExcite(nn.Layer):
    def __init__(self, c, reduction=4):
        super().__init__()
        mid = _make_divisible(c // reduction)
        self.pool = nn.AdaptiveAvgPool2D(1)
        self.fc1 = nn.Conv2D(c, mid, 1)
        self.fc2 = nn.Conv2D(mid, c, 1)
        self.relu = nn.ReLU()
        self.hsig = nn.Hardsigmoid()

    def forward(self, x):
        s = self.hsig(self.fc2(self.relu(self.fc1(self.pool(x)))))
        return x * s


class _InvertedResidualV3(nn.Layer):
    def __init__(self, in_c, exp_c, out_c, kernel, stride, use_se, act):
        super().__init__()
        self.use_res = stride == 1 and in_c == out_c
        act_layer = nn.Hardswish if act == "hswish" else nn.ReLU
        layers = []
        if exp_c != in_c:
            layers += [nn.Conv2D(in_c, exp_c, 1, bias_attr=False),
                       nn.BatchNorm2D(exp_c), act_layer()]
        layers += [nn.Conv2D(exp_c, exp_c, kernel, stride=stride,
                             padding=kernel // 2, groups=exp_c,
                             bias_attr=False),
                   nn.BatchNorm2D(exp_c), act_layer()]
        if use_se:
            layers.append(_SqueezeExcite(exp_c))
        layers += [nn.Conv2D(exp_c, out_c, 1, bias_attr=False),
                   nn.BatchNorm2D(out_c)]
        self.block = nn.Sequential(*layers)

    def forward(self, x):
        out = self.block(x)
        return x + out if self.use_res else out


# (kernel, exp, out, se, act, stride)
_SMALL = [
    (3, 16, 16, True, "relu", 2), (3, 72, 24, False, "relu", 2),
    (3, 88, 24, False, "relu", 1), (5, 96, 40, True, "hswish", 2),
    (5, 240, 40, True, "hswish", 1), (5, 240, 40, True, "hswish", 1),
    (5, 120, 48, True, "hswish", 1), (5, 144, 48, True, "hswish", 1),
    (5, 288, 96, True, "hswish", 2), (5, 576, 96, True, "hswish", 1),
    (5, 576, 96, True, "hswish", 1),
]
_LARGE = [
    (3, 16, 16, False, "relu", 1), (3, 64, 24, False, "relu", 2),
    (3, 72, 24, False, "relu", 1), (5, 72, 40, True, "relu", 2),
    (5, 120, 40, True, "relu", 1), (5, 120, 40, True, "relu", 1),
    (3, 240, 80, False, "hswish", 2), (3, 200, 80, False, "hswish", 1),
    (3, 184, 80, False, "hswish", 1), (3, 184, 80, False, "hswish", 1),
    (3, 480, 112, True, "hswish", 1), (3, 672, 112, True, "hswish", 1),
    (5, 672, 160, True, "hswish", 2), (5, 960, 160, True, "hswish", 1),
    (5, 960, 160, True, "hswish", 1),
]


class _MobileNetV3(nn.Layer):
    def __init__(self, cfg, last_exp, hidden, num_classes=1000, scale=1.0,
                 with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        sc = lambda c: _make_divisible(c * scale)  # noqa: E731
        self.stem = nn.Sequential(
            nn.Conv2D(3, sc(16), 3, stride=2, padding=1, bias_attr=False),
            nn.BatchNorm2D(sc(16)), nn.Hardswish())
        blocks = []
        in_c = sc(16)
        for k, exp, out, se, act, s in cfg:
            blocks.append(_InvertedResidualV3(
                in_c, sc(exp), sc(out), k, s, se, act))
            in_c = sc(out)
        self.blocks = nn.Sequential(*blocks)
        self.head_conv = nn.Sequential(
            nn.Conv2D(in_c, sc(last_exp), 1, bias_attr=False),
            nn.BatchNorm2D(sc(last_exp)), nn.Hardswish())
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            # hidden width: 1024 (Small) / 1280 (Large) like the upstream
            # zoo, so upstream state_dicts load shape-compatibly
            self.classifier = nn.Sequential(
                nn.Linear(sc(last_exp), hidden), nn.Hardswish(),
                nn.Dropout(0.2), nn.Linear(hidden, num_classes))

    def forward(self, x):
        from ... import tensor as T

        x = self.head_conv(self.blocks(self.stem(x)))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.classifier(T.flatten(x, 1))
        return x


class MobileNetV3Small(_MobileNetV3):
    def __init__(self, scale: float = 1.0, num_classes: int = 1000,
                 with_pool: bool = True):
        super().__init__(_SMALL, 576, 1024, num_classes, scale, with_pool)


class MobileNetV3Large(_MobileNetV3):
    def __init__(self, scale: float = 1.0, num_classes: int = 1000,
                 with_pool: bool = True):
        super().__init__(_LARGE, 960, 1280, num_classes, scale, with_pool)


def mobilenet_v3_small(scale: float = 1.0, **kw) -> MobileNetV3Small:
    return MobileNetV3Small(scale=scale, **kw)


def mobilenet_v3_large(scale: float = 1.0, **kw) -> MobileNetV3Large:
    return MobileNetV3Large(scale=scale, **kw)
