"""SqueezeNet 1.0/1.1.

The mounted reference snapshot's zoo carries lenet/mobilenet/resnet/vgg;
this model is part of the upstream paddle.vision surface the framework
targets — architecture per the original paper, API in the paddle zoo
style."""
from __future__ import annotations

from ... import nn

__all__ = ["SqueezeNet", "squeezenet1_0", "squeezenet1_1"]


class _Fire(nn.Layer):
    """squeeze 1x1 → expand 1x1 + 3x3, channel-concatenated."""

    def __init__(self, in_c, squeeze_c, e1_c, e3_c):
        super().__init__()
        self.squeeze = nn.Conv2D(in_c, squeeze_c, 1)
        self.expand1 = nn.Conv2D(squeeze_c, e1_c, 1)
        self.expand3 = nn.Conv2D(squeeze_c, e3_c, 3, padding=1)
        self.relu = nn.ReLU()

    def forward(self, x):
        from ... import tensor as T

        s = self.relu(self.squeeze(x))
        return T.concat([self.relu(self.expand1(s)),
                         self.relu(self.expand3(s))], axis=1)


class SqueezeNet(nn.Layer):
    """vision/models/squeezenet.py parity (version '1.0' or '1.1')."""

    def __init__(self, version: str = "1.0", num_classes: int = 1000):
        super().__init__()
        self.num_classes = num_classes
        if version == "1.0":
            self.features = nn.Sequential(
                nn.Conv2D(3, 96, 7, stride=2), nn.ReLU(),
                nn.MaxPool2D(3, 2),
                _Fire(96, 16, 64, 64), _Fire(128, 16, 64, 64),
                _Fire(128, 32, 128, 128),
                nn.MaxPool2D(3, 2),
                _Fire(256, 32, 128, 128), _Fire(256, 48, 192, 192),
                _Fire(384, 48, 192, 192), _Fire(384, 64, 256, 256),
                nn.MaxPool2D(3, 2),
                _Fire(512, 64, 256, 256),
            )
        elif version == "1.1":
            self.features = nn.Sequential(
                nn.Conv2D(3, 64, 3, stride=2), nn.ReLU(),
                nn.MaxPool2D(3, 2),
                _Fire(64, 16, 64, 64), _Fire(128, 16, 64, 64),
                nn.MaxPool2D(3, 2),
                _Fire(128, 32, 128, 128), _Fire(256, 32, 128, 128),
                nn.MaxPool2D(3, 2),
                _Fire(256, 48, 192, 192), _Fire(384, 48, 192, 192),
                _Fire(384, 64, 256, 256), _Fire(512, 64, 256, 256),
            )
        else:
            from ...core.errors import InvalidArgumentError

            raise InvalidArgumentError("version must be '1.0' or '1.1'")
        self.classifier = nn.Sequential(
            nn.Dropout(0.5),
            nn.Conv2D(512, num_classes, 1), nn.ReLU(),
            nn.AdaptiveAvgPool2D(1),
        )

    def forward(self, x):
        from ... import tensor as T

        x = self.classifier(self.features(x))
        return T.flatten(x, 1)


def squeezenet1_0(**kwargs) -> SqueezeNet:
    return SqueezeNet("1.0", **kwargs)


def squeezenet1_1(**kwargs) -> SqueezeNet:
    return SqueezeNet("1.1", **kwargs)
