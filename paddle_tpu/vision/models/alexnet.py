"""AlexNet.

The mounted reference snapshot's zoo carries lenet/mobilenet/resnet/vgg;
this model is part of the upstream paddle.vision surface the framework
targets — architecture per the original paper, API in the paddle zoo
style."""
from __future__ import annotations

from ... import nn

__all__ = ["AlexNet", "alexnet"]


class AlexNet(nn.Layer):
    """AlexNet for 3x224x224 inputs (vision/models/alexnet.py parity)."""

    def __init__(self, num_classes: int = 1000, dropout: float = 0.5):
        super().__init__()
        self.num_classes = num_classes
        self.features = nn.Sequential(
            nn.Conv2D(3, 64, 11, stride=4, padding=2), nn.ReLU(),
            nn.MaxPool2D(3, 2),
            nn.Conv2D(64, 192, 5, padding=2), nn.ReLU(),
            nn.MaxPool2D(3, 2),
            nn.Conv2D(192, 384, 3, padding=1), nn.ReLU(),
            nn.Conv2D(384, 256, 3, padding=1), nn.ReLU(),
            nn.Conv2D(256, 256, 3, padding=1), nn.ReLU(),
            nn.MaxPool2D(3, 2),
        )
        self.avgpool = nn.AdaptiveAvgPool2D((6, 6))
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Dropout(dropout), nn.Linear(256 * 6 * 6, 4096), nn.ReLU(),
                nn.Dropout(dropout), nn.Linear(4096, 4096), nn.ReLU(),
                nn.Linear(4096, num_classes),
            )

    def forward(self, x):
        from ... import tensor as T

        x = self.avgpool(self.features(x))
        if self.num_classes > 0:
            x = self.classifier(T.flatten(x, 1))
        return x


def alexnet(pretrained: bool = False, **kwargs) -> AlexNet:
    if pretrained:
        raise NotImplementedError(
            "pretrained weights are not bundled (no downloader in this "
            "build); load a converted state_dict with set_state_dict")
    return AlexNet(**kwargs)
