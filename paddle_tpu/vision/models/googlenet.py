"""GoogLeNet / Inception-v1.

The mounted reference snapshot's zoo carries lenet/mobilenet/resnet/vgg;
GoogLeNet is part of the upstream paddle.vision surface this framework
targets — architecture per Szegedy et al. 2014, API in the paddle zoo
style, including the upstream contract of returning
``(out, aux1, aux2)`` from every forward (train AND eval; callers weight
the aux logits into the loss)."""
from __future__ import annotations

from ... import nn

__all__ = ["GoogLeNet", "googlenet"]


class _Inception(nn.Layer):
    """Four parallel branches concatenated on channels."""

    def __init__(self, in_c, c1, c3r, c3, c5r, c5, proj):
        super().__init__()
        self.b1 = nn.Sequential(nn.Conv2D(in_c, c1, 1), nn.ReLU())
        self.b3 = nn.Sequential(
            nn.Conv2D(in_c, c3r, 1), nn.ReLU(),
            nn.Conv2D(c3r, c3, 3, padding=1), nn.ReLU())
        self.b5 = nn.Sequential(
            nn.Conv2D(in_c, c5r, 1), nn.ReLU(),
            nn.Conv2D(c5r, c5, 5, padding=2), nn.ReLU())
        self.bp = nn.Sequential(
            nn.MaxPool2D(3, 1, padding=1),
            nn.Conv2D(in_c, proj, 1), nn.ReLU())

    def forward(self, x):
        from ... import tensor as T

        return T.concat([self.b1(x), self.b3(x), self.b5(x), self.bp(x)],
                        axis=1)


class _AuxHead(nn.Layer):
    """Side classifier off 4a/4d (paper §5; upstream GoogLeNet's out1/out2)."""

    def __init__(self, in_c, num_classes):
        super().__init__()
        self.pool = nn.AdaptiveAvgPool2D(4)
        self.conv = nn.Conv2D(in_c, 128, 1)
        self.relu = nn.ReLU()
        self.fc1 = nn.Linear(128 * 16, 1024)
        self.dropout = nn.Dropout(0.7)
        self.fc2 = nn.Linear(1024, num_classes)

    def forward(self, x):
        from ... import tensor as T

        x = self.relu(self.conv(self.pool(x)))
        x = self.relu(self.fc1(T.flatten(x, 1)))
        return self.fc2(self.dropout(x))


class GoogLeNet(nn.Layer):
    """Returns ``(out, aux1, aux2)`` like upstream paddle's GoogLeNet —
    aux heads hang off inception 4a and 4d."""

    def __init__(self, num_classes: int = 1000):
        super().__init__()
        self.stem = nn.Sequential(
            nn.Conv2D(3, 64, 7, stride=2, padding=3), nn.ReLU(),
            nn.MaxPool2D(3, 2, padding=1),
            nn.Conv2D(64, 64, 1), nn.ReLU(),
            nn.Conv2D(64, 192, 3, padding=1), nn.ReLU(),
            nn.MaxPool2D(3, 2, padding=1),
        )
        self.pre = nn.Sequential(
            _Inception(192, 64, 96, 128, 16, 32, 32),    # 3a → 256
            _Inception(256, 128, 128, 192, 32, 96, 64),  # 3b → 480
            nn.MaxPool2D(3, 2, padding=1),
            _Inception(480, 192, 96, 208, 16, 48, 64),   # 4a → 512
        )
        self.mid = nn.Sequential(
            _Inception(512, 160, 112, 224, 24, 64, 64),  # 4b
            _Inception(512, 128, 128, 256, 24, 64, 64),  # 4c
            _Inception(512, 112, 144, 288, 32, 64, 64),  # 4d → 528
        )
        self.post = nn.Sequential(
            _Inception(528, 256, 160, 320, 32, 128, 128),  # 4e → 832
            nn.MaxPool2D(3, 2, padding=1),
            _Inception(832, 256, 160, 320, 32, 128, 128),  # 5a
            _Inception(832, 384, 192, 384, 48, 128, 128),  # 5b → 1024
        )
        self.aux1 = _AuxHead(512, num_classes)
        self.aux2 = _AuxHead(528, num_classes)
        self.pool = nn.AdaptiveAvgPool2D(1)
        self.dropout = nn.Dropout(0.4)
        self.fc = nn.Linear(1024, num_classes)

    def forward(self, x):
        from ... import tensor as T

        h4a = self.pre(self.stem(x))
        h4d = self.mid(h4a)
        h = self.pool(self.post(h4d))
        out = self.fc(self.dropout(T.flatten(h, 1)))
        return out, self.aux1(h4a), self.aux2(h4d)


def googlenet(**kwargs) -> GoogLeNet:
    return GoogLeNet(**kwargs)
