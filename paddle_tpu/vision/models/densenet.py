"""DenseNet-121/161/169/201/264.

The mounted reference snapshot's zoo carries lenet/mobilenet/resnet/vgg;
this model is part of the upstream paddle.vision surface the framework
targets — architecture per the original paper, API in the paddle zoo
style."""
from __future__ import annotations

from ... import nn

__all__ = ["DenseNet", "densenet121", "densenet161", "densenet169",
           "densenet201", "densenet264"]

_CFG = {
    121: (64, 32, (6, 12, 24, 16)),
    161: (96, 48, (6, 12, 36, 24)),
    169: (64, 32, (6, 12, 32, 32)),
    201: (64, 32, (6, 12, 48, 32)),
    264: (64, 32, (6, 12, 64, 48)),
}


class _DenseLayer(nn.Layer):
    """BN→ReLU→1x1(bn_size*k)→BN→ReLU→3x3(k), output concatenated."""

    def __init__(self, in_c, growth, bn_size=4):
        super().__init__()
        mid = bn_size * growth
        self.norm1 = nn.BatchNorm2D(in_c)
        self.conv1 = nn.Conv2D(in_c, mid, 1, bias_attr=False)
        self.norm2 = nn.BatchNorm2D(mid)
        self.conv2 = nn.Conv2D(mid, growth, 3, padding=1, bias_attr=False)
        self.relu = nn.ReLU()

    def forward(self, x):
        from ... import tensor as T

        out = self.conv1(self.relu(self.norm1(x)))
        out = self.conv2(self.relu(self.norm2(out)))
        return T.concat([x, out], axis=1)


class _Transition(nn.Sequential):
    def __init__(self, in_c, out_c):
        super().__init__(
            nn.BatchNorm2D(in_c), nn.ReLU(),
            nn.Conv2D(in_c, out_c, 1, bias_attr=False),
            nn.AvgPool2D(2, 2),
        )


class DenseNet(nn.Layer):
    """vision/models/densenet.py parity (layers selects the config)."""

    def __init__(self, layers: int = 121, num_classes: int = 1000,
                 bn_size: int = 4, block_config=None, growth_rate=None):
        """``layers`` picks a standard config; ``block_config``/``growth_rate``
        override it for custom/small variants (CIFAR-style DenseNets)."""
        super().__init__()
        if layers not in _CFG:
            from ...core.errors import InvalidArgumentError

            raise InvalidArgumentError(
                "DenseNet layers must be one of %s" % sorted(_CFG))
        init_c, growth, blocks = _CFG[layers]
        if block_config is not None:
            blocks = tuple(block_config)
        if growth_rate is not None:
            growth = int(growth_rate)
            init_c = 2 * growth
        self.stem = nn.Sequential(
            nn.Conv2D(3, init_c, 7, stride=2, padding=3, bias_attr=False),
            nn.BatchNorm2D(init_c), nn.ReLU(), nn.MaxPool2D(3, 2, padding=1))
        feats = []
        c = init_c
        for i, n in enumerate(blocks):
            for _ in range(n):
                feats.append(_DenseLayer(c, growth, bn_size))
                c += growth
            if i + 1 < len(blocks):
                feats.append(_Transition(c, c // 2))
                c //= 2
        self.features = nn.Sequential(*feats)
        self.norm = nn.BatchNorm2D(c)
        self.relu = nn.ReLU()
        self.pool = nn.AdaptiveAvgPool2D(1)
        self.classifier = nn.Linear(c, num_classes)

    def forward(self, x):
        from ... import tensor as T

        x = self.pool(self.relu(self.norm(self.features(self.stem(x)))))
        return self.classifier(T.flatten(x, 1))


def densenet121(**kw):
    return DenseNet(121, **kw)


def densenet161(**kw):
    return DenseNet(161, **kw)


def densenet169(**kw):
    return DenseNet(169, **kw)


def densenet201(**kw):
    return DenseNet(201, **kw)


def densenet264(**kw):
    return DenseNet(264, **kw)
