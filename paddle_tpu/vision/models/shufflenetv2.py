"""ShuffleNetV2.

The mounted reference snapshot's zoo carries lenet/mobilenet/resnet/vgg;
this model is part of the upstream paddle.vision surface the framework
targets — architecture per the original paper, API in the paddle zoo
style."""
from __future__ import annotations

from ... import nn

__all__ = ["ShuffleNetV2", "shufflenet_v2_x0_25", "shufflenet_v2_x0_5",
           "shufflenet_v2_x1_0", "shufflenet_v2_x1_5", "shufflenet_v2_x2_0"]

_STAGE_OUT = {
    0.25: (24, 24, 48, 96, 512),
    0.5: (24, 48, 96, 192, 1024),
    1.0: (24, 116, 232, 464, 1024),
    1.5: (24, 176, 352, 704, 1024),
    2.0: (24, 244, 488, 976, 2048),
}


def _channel_shuffle(x, groups: int):
    from ... import tensor as T

    n, c, h, w = x.shape
    x = T.reshape(x, [n, groups, c // groups, h, w])
    x = T.transpose(x, [0, 2, 1, 3, 4])
    return T.reshape(x, [n, c, h, w])


class _Unit(nn.Layer):
    """Stride-1 split unit / stride-2 downsample unit + channel shuffle."""

    def __init__(self, in_c, out_c, stride):
        super().__init__()
        self.stride = stride
        branch_c = out_c // 2
        if stride == 1:
            main_in = in_c // 2
        else:
            main_in = in_c
            self.short = nn.Sequential(
                nn.Conv2D(in_c, in_c, 3, stride=2, padding=1, groups=in_c,
                          bias_attr=False),
                nn.BatchNorm2D(in_c),
                nn.Conv2D(in_c, branch_c, 1, bias_attr=False),
                nn.BatchNorm2D(branch_c), nn.ReLU(),
            )
        self.main = nn.Sequential(
            nn.Conv2D(main_in, branch_c, 1, bias_attr=False),
            nn.BatchNorm2D(branch_c), nn.ReLU(),
            nn.Conv2D(branch_c, branch_c, 3, stride=stride, padding=1,
                      groups=branch_c, bias_attr=False),
            nn.BatchNorm2D(branch_c),
            nn.Conv2D(branch_c, branch_c, 1, bias_attr=False),
            nn.BatchNorm2D(branch_c), nn.ReLU(),
        )

    def forward(self, x):
        from ... import tensor as T

        if self.stride == 1:
            c = x.shape[1] // 2
            a, b = x[:, :c], x[:, c:]
            out = T.concat([a, self.main(b)], axis=1)
        else:
            out = T.concat([self.short(x), self.main(x)], axis=1)
        return _channel_shuffle(out, 2)


class ShuffleNetV2(nn.Layer):
    """vision/models/shufflenetv2.py parity (scale selects widths)."""

    def __init__(self, scale: float = 1.0, num_classes: int = 1000):
        super().__init__()
        if scale not in _STAGE_OUT:
            from ...core.errors import InvalidArgumentError

            raise InvalidArgumentError(
                "scale must be one of %s" % sorted(_STAGE_OUT))
        c0, c1, c2, c3, c4 = _STAGE_OUT[scale]
        self.stem = nn.Sequential(
            nn.Conv2D(3, c0, 3, stride=2, padding=1, bias_attr=False),
            nn.BatchNorm2D(c0), nn.ReLU(), nn.MaxPool2D(3, 2, padding=1))
        stages = []
        in_c = c0
        for out_c, repeats in ((c1, 4), (c2, 8), (c3, 4)):
            stages.append(_Unit(in_c, out_c, stride=2))
            for _ in range(repeats - 1):
                stages.append(_Unit(out_c, out_c, stride=1))
            in_c = out_c
        self.stages = nn.Sequential(*stages)
        self.head = nn.Sequential(
            nn.Conv2D(in_c, c4, 1, bias_attr=False),
            nn.BatchNorm2D(c4), nn.ReLU(), nn.AdaptiveAvgPool2D(1))
        self.classifier = nn.Linear(c4, num_classes)

    def forward(self, x):
        from ... import tensor as T

        x = self.head(self.stages(self.stem(x)))
        return self.classifier(T.flatten(x, 1))


def shufflenet_v2_x0_25(**kw):
    return ShuffleNetV2(0.25, **kw)


def shufflenet_v2_x0_5(**kw):
    return ShuffleNetV2(0.5, **kw)


def shufflenet_v2_x1_0(**kw):
    return ShuffleNetV2(1.0, **kw)


def shufflenet_v2_x1_5(**kw):
    return ShuffleNetV2(1.5, **kw)


def shufflenet_v2_x2_0(**kw):
    return ShuffleNetV2(2.0, **kw)
