"""ResNet family (reference: python/paddle/vision/models/resnet.py).

BASELINE.md config #2's model.  TPU notes: the public contract stays NCHW
(paddle parity — inputs are NCHW and the state_dict is identical either
way, since Conv2D weights are OIHW in both formats), but the whole compute
graph can run channels-last with ``data_format="NHWC"``: inputs are
transposed once at entry and every conv/BN/pool operates NHWC — the
layout the TPU's conv lowering is native in, sparing XLA per-op logical
transposes.  BatchNorm runs through the framework's functional batch_norm
whose running stats thread through jit as mutable buffers.
"""
from __future__ import annotations

import functools
from typing import List, Optional, Type, Union

from ... import nn

__all__ = ["ResNet", "resnet18", "resnet34", "resnet50", "resnet101",
           "resnet152", "wide_resnet50_2", "wide_resnet101_2"]


class BasicBlock(nn.Layer):
    expansion = 1

    def __init__(self, inplanes, planes, stride=1, downsample=None,
                 groups=1, base_width=64, dilation=1, norm_layer=None,
                 data_format="NCHW"):
        super().__init__()
        norm_layer = norm_layer or functools.partial(
            nn.BatchNorm2D, data_format=data_format)
        if groups != 1 or base_width != 64:
            raise ValueError("BasicBlock only supports groups=1, base_width=64")
        self.conv1 = nn.Conv2D(inplanes, planes, 3, stride=stride, padding=1,
                               bias_attr=False, data_format=data_format)
        self.bn1 = norm_layer(planes)
        self.relu = nn.ReLU()
        self.conv2 = nn.Conv2D(planes, planes, 3, padding=1, bias_attr=False,
                               data_format=data_format)
        self.bn2 = norm_layer(planes)
        self.downsample = downsample
        self.stride = stride

    def forward(self, x):
        identity = x
        out = self.relu(self.bn1(self.conv1(x)))
        out = self.bn2(self.conv2(out))
        if self.downsample is not None:
            identity = self.downsample(x)
        return self.relu(out + identity)


class BottleneckBlock(nn.Layer):
    expansion = 4

    def __init__(self, inplanes, planes, stride=1, downsample=None,
                 groups=1, base_width=64, dilation=1, norm_layer=None,
                 data_format="NCHW"):
        super().__init__()
        norm_layer = norm_layer or functools.partial(
            nn.BatchNorm2D, data_format=data_format)
        width = int(planes * (base_width / 64.0)) * groups
        self.conv1 = nn.Conv2D(inplanes, width, 1, bias_attr=False,
                               data_format=data_format)
        self.bn1 = norm_layer(width)
        self.conv2 = nn.Conv2D(width, width, 3, stride=stride, padding=dilation,
                               groups=groups, dilation=dilation,
                               bias_attr=False, data_format=data_format)
        self.bn2 = norm_layer(width)
        self.conv3 = nn.Conv2D(width, planes * self.expansion, 1,
                               bias_attr=False, data_format=data_format)
        self.bn3 = norm_layer(planes * self.expansion)
        self.relu = nn.ReLU()
        self.downsample = downsample

    def forward(self, x):
        identity = x
        out = self.relu(self.bn1(self.conv1(x)))
        out = self.relu(self.bn2(self.conv2(out)))
        out = self.bn3(self.conv3(out))
        if self.downsample is not None:
            identity = self.downsample(x)
        return self.relu(out + identity)


def _space_to_depth_stem(x_nhwc, w_oihw):
    """The 7x7/s2 stem conv as an MXU-friendly 4x4/s1 conv.

    The stem's 3 input channels starve the MXU's 128-deep contraction
    lanes (K = 7*7*3 = 147 over a 224x224 window).  The classic TPU
    rewrite (used by MLPerf ResNet submissions) regroups input pixels by
    parity — [N,H,W,3] -> [N,H/2,W/2,12] — and scatters the 7x7x3 kernel
    into an equivalent 4x4x12 one, giving a stride-1 conv with K = 192.
    Each output pixel sums exactly the same input*weight products as the
    original conv (summation order differs, so fp32 agreement is ~1e-5;
    asserted by tests/test_models.py::test_space_to_depth_stem_exact).

    Derivation: original tap kh in [0,7) touches input row 2*ho + kh - 3,
    whose parity is (kh+1) % 2 and whose s2d row offset is
    (kh+1)//2 - 2 in [-2,1] — a 4-tap window with asymmetric padding
    (2, 1).  Same in w.  Weight layout: OIHW in, transformed to HWIO with
    the s2d channel order (ph, pw, ci).
    """
    import jax.numpy as jnp
    from jax import lax

    # dtype alignment mirrors the conv white-list cast (covers O2-decorated
    # bf16 weights with fp32 inputs and vice versa)
    if w_oihw.dtype != x_nhwc.dtype:
        w_oihw = w_oihw.astype(x_nhwc.dtype)
    block = 2  # the derivation is FIXED to the 7x7/stride-2/pad-3 stem
    n, h, w, ci = x_nhwc.shape
    co = w_oihw.shape[0]
    k = w_oihw.shape[2]
    # input: group 2x2 pixel parities into channels -> [N, H/2, W/2, 4*ci]
    x2 = x_nhwc.reshape(n, h // block, block, w // block, block, ci)
    x2 = x2.transpose(0, 1, 3, 2, 4, 5).reshape(
        n, h // block, w // block, block * block * ci)
    # kernel: scatter K[kh,kw] into K2[(kh+1)//2, (kw+1)//2, ph, pw]
    w_hwio = jnp.transpose(w_oihw, (2, 3, 1, 0))  # [7,7,ci,co]
    k2 = jnp.zeros((4, 2, 4, 2, ci, co), w_hwio.dtype)
    kh = jnp.arange(k)
    d, p = (kh + 1) // 2, (kh + 1) % 2
    k2 = k2.at[d[:, None], p[:, None], d[None, :], p[None, :]].set(
        w_hwio)  # [dh, ph, dw, pw, ci, co]
    k2 = k2.transpose(0, 2, 1, 3, 4, 5).reshape(4, 4, block * block * ci, co)
    dn = lax.conv_dimension_numbers(x2.shape, k2.shape,
                                    ("NHWC", "HWIO", "NHWC"))
    return lax.conv_general_dilated(
        x2, k2, window_strides=(1, 1), padding=((2, 1), (2, 1)),
        dimension_numbers=dn)


from ...framework.dispatch import make_op as _make_op

_s2d_op = _make_op(_space_to_depth_stem, op_name="s2d_stem")


class ResNet(nn.Layer):
    """vision/models/resnet.py ResNet parity.

    ``data_format="NHWC"`` runs the conv stack channels-last (TPU-native);
    inputs remain NCHW at the public boundary and are transposed once.
    ``space_to_depth_stem=True`` (NHWC only) rewrites the 7x7/s2 stem as
    the numerically-equivalent MXU-friendly 4x4/s1 conv over
    parity-grouped pixels; the state_dict keeps the canonical 7x7 weight.
    """

    def __init__(self, block, depth: int = 50,
                 layers: Optional[List[int]] = None, num_classes: int = 1000,
                 with_pool: bool = True, groups: int = 1, width: int = 64,
                 data_format: str = "NCHW",
                 space_to_depth_stem: bool = False):
        super().__init__()
        layer_cfg = {18: [2, 2, 2, 2], 34: [3, 4, 6, 3], 50: [3, 4, 6, 3],
                     101: [3, 4, 23, 3], 152: [3, 8, 36, 3]}
        if layers is None and depth not in layer_cfg:
            raise ValueError(
                "ResNet depth must be one of %s (or pass layers=), got %r"
                % (sorted(layer_cfg), depth))
        if data_format not in ("NCHW", "NHWC"):
            raise ValueError("data_format must be NCHW or NHWC, got %r"
                             % (data_format,))
        if space_to_depth_stem and data_format != "NHWC":
            raise ValueError(
                "space_to_depth_stem requires data_format='NHWC'")
        self.space_to_depth_stem = bool(space_to_depth_stem)
        layers = layers or layer_cfg[depth]
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.groups = groups
        self.base_width = width
        self.data_format = data_format
        self._norm_layer = functools.partial(nn.BatchNorm2D,
                                             data_format=data_format)
        self.inplanes = 64
        self.dilation = 1

        self.conv1 = nn.Conv2D(3, self.inplanes, 7, stride=2, padding=3,
                               bias_attr=False, data_format=data_format)
        self.bn1 = self._norm_layer(self.inplanes)
        self.relu = nn.ReLU()
        self.maxpool = nn.MaxPool2D(3, stride=2, padding=1,
                                    data_format=data_format)
        self.layer1 = self._make_layer(block, 64, layers[0])
        self.layer2 = self._make_layer(block, 128, layers[1], stride=2)
        self.layer3 = self._make_layer(block, 256, layers[2], stride=2)
        self.layer4 = self._make_layer(block, 512, layers[3], stride=2)
        if with_pool:
            self.avgpool = nn.AdaptiveAvgPool2D((1, 1),
                                                data_format=data_format)
        if num_classes > 0:
            self.fc = nn.Linear(512 * block.expansion, num_classes)

    def _make_layer(self, block, planes, blocks, stride=1):
        norm_layer = self._norm_layer
        downsample = None
        if stride != 1 or self.inplanes != planes * block.expansion:
            downsample = nn.Sequential(
                nn.Conv2D(self.inplanes, planes * block.expansion, 1,
                          stride=stride, bias_attr=False,
                          data_format=self.data_format),
                norm_layer(planes * block.expansion),
            )
        layers = [block(self.inplanes, planes, stride, downsample,
                        self.groups, self.base_width, 1, norm_layer,
                        data_format=self.data_format)]
        self.inplanes = planes * block.expansion
        for _ in range(1, blocks):
            layers.append(block(self.inplanes, planes,
                                groups=self.groups,
                                base_width=self.base_width,
                                norm_layer=norm_layer,
                                data_format=self.data_format))
        return nn.Sequential(*layers)

    def forward(self, x):
        from ... import tensor as T

        if self.data_format == "NHWC":
            # public contract stays NCHW; one transpose at entry puts the
            # whole stack channels-last
            x = T.transpose(x, [0, 2, 3, 1])
        # the s2d rewrite needs even spatial dims (parity grouping) and the
        # canonical 7x7 stem; anything else falls back to the plain conv
        if self.space_to_depth_stem and x.shape[1] % 2 == 0 \
                and x.shape[2] % 2 == 0 \
                and self.conv1.weight.shape[-1] == 7:
            x = self.relu(self.bn1(_s2d_op(x, self.conv1.weight)))
        else:
            x = self.relu(self.bn1(self.conv1(x)))
        x = self.maxpool(x)
        x = self.layer1(x)
        x = self.layer2(x)
        x = self.layer3(x)
        x = self.layer4(x)
        if self.with_pool:
            x = self.avgpool(x)
        if self.data_format == "NHWC":
            # restore the NCHW public contract before flatten/return, so
            # feature-extractor outputs and fc weights are layout-invariant
            x = T.transpose(x, [0, 3, 1, 2])
        if self.num_classes > 0:
            x = T.flatten(x, 1)
            x = self.fc(x)
        return x


def _resnet(block, depth, pretrained=False, **kwargs):
    if pretrained:
        raise NotImplementedError(
            "pretrained weights are not bundled (no-egress build); load a "
            "checkpoint with set_state_dict instead")
    return ResNet(block, depth, **kwargs)


def resnet18(pretrained=False, **kwargs):
    return _resnet(BasicBlock, 18, pretrained, **kwargs)


def resnet34(pretrained=False, **kwargs):
    return _resnet(BasicBlock, 34, pretrained, **kwargs)


def resnet50(pretrained=False, **kwargs):
    return _resnet(BottleneckBlock, 50, pretrained, **kwargs)


def resnet101(pretrained=False, **kwargs):
    return _resnet(BottleneckBlock, 101, pretrained, **kwargs)


def resnet152(pretrained=False, **kwargs):
    return _resnet(BottleneckBlock, 152, pretrained, **kwargs)


def wide_resnet50_2(pretrained=False, **kwargs):
    kwargs["width"] = 128
    return _resnet(BottleneckBlock, 50, pretrained, **kwargs)


def wide_resnet101_2(pretrained=False, **kwargs):
    kwargs["width"] = 128
    return _resnet(BottleneckBlock, 101, pretrained, **kwargs)
