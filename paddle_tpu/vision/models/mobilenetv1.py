"""MobileNetV1 (reference: python/paddle/vision/models/mobilenetv1.py)."""
from __future__ import annotations

from ... import nn

__all__ = ["MobileNetV1", "mobilenet_v1"]


def _round(c: float) -> int:
    return max(1, int(c))


class _DepthwiseSeparable(nn.Layer):
    """3x3 depthwise + 1x1 pointwise, each Conv-BN-ReLU."""

    def __init__(self, in_c, out_c, stride):
        super().__init__()
        self.dw = nn.Sequential(
            nn.Conv2D(in_c, in_c, 3, stride=stride, padding=1, groups=in_c,
                      bias_attr=False),
            nn.BatchNorm2D(in_c), nn.ReLU())
        self.pw = nn.Sequential(
            nn.Conv2D(in_c, out_c, 1, bias_attr=False),
            nn.BatchNorm2D(out_c), nn.ReLU())

    def forward(self, x):
        return self.pw(self.dw(x))


class MobileNetV1(nn.Layer):
    """mobilenetv1.py:84 parity (scale / num_classes / with_pool knobs)."""

    def __init__(self, scale: float = 1.0, num_classes: int = 1000,
                 with_pool: bool = True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        s = scale
        self.stem = nn.Sequential(
            nn.Conv2D(3, _round(32 * s), 3, stride=2, padding=1,
                      bias_attr=False),
            nn.BatchNorm2D(_round(32 * s)), nn.ReLU())
        cfg = [  # (in, out, stride), all x scale
            (32, 64, 1), (64, 128, 2), (128, 128, 1), (128, 256, 2),
            (256, 256, 1), (256, 512, 2),
            (512, 512, 1), (512, 512, 1), (512, 512, 1), (512, 512, 1),
            (512, 512, 1),
            (512, 1024, 2), (1024, 1024, 1),
        ]
        self.blocks = nn.Sequential(*[
            _DepthwiseSeparable(_round(i * s), _round(o * s), st)
            for i, o, st in cfg])
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.fc = nn.Linear(_round(1024 * s), num_classes)

    def forward(self, x):
        from ... import tensor as T

        x = self.blocks(self.stem(x))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(T.flatten(x, 1))
        return x


def mobilenet_v1(scale: float = 1.0, **kwargs) -> MobileNetV1:
    return MobileNetV1(scale=scale, **kwargs)
