"""Detection ops (reference ``python/paddle/vision/ops.py`` +
``fluid/layers/detection.py``: yolo_box, nms/multiclass_nms, box_coder,
box IoU, roi_align).

TPU-native design: everything is static-shape.  NMS — inherently a
sequential suppression — is expressed as a fixed-trip ``lax.scan`` over a
score-sorted candidate list with a suppression mask (no dynamic output
size: callers get ``max_out`` indices + a validity count, the standard XLA
detection formulation).  ``roi_align`` is gather + bilinear weights, which
XLA fuses into a few dense ops rather than the reference's custom CUDA
kernel (``roi_align_op.cu``).
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..core.errors import InvalidArgumentError

__all__ = ["box_iou", "nms", "box_coder", "yolo_box", "roi_align",
           "deform_conv2d", "DeformConv2D", "read_file", "decode_jpeg",
           "yolo_loss"]


def box_iou(boxes1, boxes2):
    """Pairwise IoU for [N,4] / [M,4] xyxy boxes → [N,M]."""
    b1 = jnp.asarray(boxes1)[:, None, :]
    b2 = jnp.asarray(boxes2)[None, :, :]
    lt = jnp.maximum(b1[..., :2], b2[..., :2])
    rb = jnp.minimum(b1[..., 2:], b2[..., 2:])
    wh = jnp.clip(rb - lt, 0)
    inter = wh[..., 0] * wh[..., 1]
    a1 = (b1[..., 2] - b1[..., 0]) * (b1[..., 3] - b1[..., 1])
    a2 = (b2[..., 2] - b2[..., 0]) * (b2[..., 3] - b2[..., 1])
    return inter / jnp.maximum(a1 + a2 - inter, 1e-9)


def nms(boxes, scores, iou_threshold: float = 0.5,
        max_out: Optional[int] = None,
        score_threshold: Optional[float] = None) -> Tuple:
    """Greedy hard NMS (``nms_op.cc`` semantics, static shapes).

    Returns ``(indices[max_out] int32, count int32)``: the first ``count``
    entries of ``indices`` select kept boxes in descending-score order;
    the tail is padded with -1.  Fixed trip count = max_out scan steps, so
    one compilation serves every input.
    """
    boxes = jnp.asarray(boxes)
    scores = jnp.asarray(scores)
    n = boxes.shape[0]
    if max_out is None:
        max_out = n
    order = jnp.argsort(-scores)
    sorted_boxes = boxes[order]
    iou = box_iou(sorted_boxes, sorted_boxes)
    alive = jnp.ones((n,), bool)
    if score_threshold is not None:
        alive = alive & (scores[order] > score_threshold)

    def body(state, _):
        alive, count, out = state
        # highest-score still-alive candidate (n = none left)
        cand = jnp.argmax(alive)  # first True (argmax of bool)
        any_alive = alive.any()
        out = out.at[count].set(jnp.where(any_alive, order[cand], -1))
        suppress = iou[cand] > iou_threshold
        alive = alive & ~suppress & (jnp.arange(n) != cand)
        alive = jnp.where(any_alive, alive, jnp.zeros_like(alive))
        count = count + jnp.where(any_alive, 1, 0)
        return (alive, count, out), None

    init = (alive, jnp.int32(0), jnp.full((max_out,), -1, jnp.int32))
    (alive, count, out), _ = lax.scan(body, init, None, length=max_out)
    return out, count


def box_coder(prior_box, prior_box_var, target_box,
              code_type: str = "encode_center_size",
              box_normalized: bool = True):
    """box_coder_op.cc parity: encode/decode boxes against priors.

    priors/targets: [N, 4] xyxy.  ``decode_center_size`` treats target_box
    as deltas [N, 4] (dx, dy, dw, dh).
    """
    pb = jnp.asarray(prior_box, jnp.float32)
    pv = jnp.asarray(prior_box_var, jnp.float32)
    tb = jnp.asarray(target_box, jnp.float32)
    norm = 0.0 if box_normalized else 1.0
    pw = pb[:, 2] - pb[:, 0] + norm
    ph = pb[:, 3] - pb[:, 1] + norm
    pcx = pb[:, 0] + pw * 0.5
    pcy = pb[:, 1] + ph * 0.5
    if code_type == "encode_center_size":
        tw = tb[:, 2] - tb[:, 0] + norm
        th = tb[:, 3] - tb[:, 1] + norm
        tcx = tb[:, 0] + tw * 0.5
        tcy = tb[:, 1] + th * 0.5
        out = jnp.stack([(tcx - pcx) / pw, (tcy - pcy) / ph,
                         jnp.log(tw / pw), jnp.log(th / ph)], axis=1)
        return out / pv
    if code_type == "decode_center_size":
        d = tb * pv
        cx = d[:, 0] * pw + pcx
        cy = d[:, 1] * ph + pcy
        w = jnp.exp(d[:, 2]) * pw
        h = jnp.exp(d[:, 3]) * ph
        return jnp.stack([cx - w * 0.5, cy - h * 0.5,
                          cx + w * 0.5 - norm, cy + h * 0.5 - norm], axis=1)
    raise InvalidArgumentError("code_type must be encode/decode_center_size")


def yolo_box(x, img_size, anchors, class_num: int, conf_thresh: float,
             downsample_ratio: int = 32, clip_bbox: bool = True,
             scale_x_y: float = 1.0):
    """yolo_box_op.cc parity: decode one YOLO head.

    ``x``: [N, len(anchors)/2*(5+class_num), H, W]; returns
    (boxes [N, H*W*A, 4] xyxy in image coords, scores [N, H*W*A, classes]).
    Low-confidence boxes get zeroed scores (the reference zeroes the box;
    zero scores is the mask-friendly equivalent for static shapes).
    """
    x = jnp.asarray(x)
    n, c, h, w = x.shape
    na = len(anchors) // 2
    if c != na * (5 + class_num):
        raise InvalidArgumentError(
            "yolo_box channel mismatch: %d != %d*(5+%d)"
            % (c, na, class_num))
    anchors = np.asarray(anchors, np.float32).reshape(na, 2)
    feats = x.reshape(n, na, 5 + class_num, h, w)
    grid_x = jnp.arange(w, dtype=jnp.float32)[None, None, None, :]
    grid_y = jnp.arange(h, dtype=jnp.float32)[None, None, :, None]
    alpha, beta = scale_x_y, -0.5 * (scale_x_y - 1.0)
    bx = (jax.nn.sigmoid(feats[:, :, 0]) * alpha + beta + grid_x) / w
    by = (jax.nn.sigmoid(feats[:, :, 1]) * alpha + beta + grid_y) / h
    input_w = w * downsample_ratio
    input_h = h * downsample_ratio
    bw = jnp.exp(feats[:, :, 2]) * anchors[None, :, 0, None, None] / input_w
    bh = jnp.exp(feats[:, :, 3]) * anchors[None, :, 1, None, None] / input_h
    conf = jax.nn.sigmoid(feats[:, :, 4])
    probs = jax.nn.sigmoid(feats[:, :, 5:]) * conf[:, :, None]
    img_size = jnp.asarray(img_size, jnp.float32)  # [N, 2] (h, w)
    img_h = img_size[:, 0][:, None, None, None]
    img_w = img_size[:, 1][:, None, None, None]
    x0 = (bx - bw * 0.5) * img_w
    y0 = (by - bh * 0.5) * img_h
    x1 = (bx + bw * 0.5) * img_w
    y1 = (by + bh * 0.5) * img_h
    if clip_bbox:
        x0 = jnp.clip(x0, 0, img_w - 1)
        y0 = jnp.clip(y0, 0, img_h - 1)
        x1 = jnp.clip(x1, 0, img_w - 1)
        y1 = jnp.clip(y1, 0, img_h - 1)
    boxes = jnp.stack([x0, y0, x1, y1], axis=-1).reshape(n, -1, 4)
    keep = (conf > conf_thresh)[..., None]
    scores = jnp.where(keep, probs.transpose(0, 1, 3, 4, 2),
                       0.0).reshape(n, -1, class_num)
    return boxes, scores


def roi_align(x, boxes, boxes_num, output_size, spatial_scale: float = 1.0,
              sampling_ratio: int = -1, aligned: bool = True):
    """roi_align_op parity: [N,C,H,W] + [R,4] xyxy rois → [R,C,oh,ow].

    Bilinear sampling as dense gathers; ``boxes_num`` [N] maps each roi to
    its batch image (the LoD replacement, consistent with tensor.segment).
    """
    x = jnp.asarray(x)
    boxes = jnp.asarray(boxes, jnp.float32)
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = output_size
    n, c, h, w = x.shape
    r = boxes.shape[0]
    batch_idx = jnp.repeat(jnp.arange(n), jnp.asarray(boxes_num),
                           total_repeat_length=r)
    offset = 0.5 if aligned else 0.0
    x0 = boxes[:, 0] * spatial_scale - offset
    y0 = boxes[:, 1] * spatial_scale - offset
    x1 = boxes[:, 2] * spatial_scale - offset
    y1 = boxes[:, 3] * spatial_scale - offset
    rw = jnp.maximum(x1 - x0, 1e-3 if aligned else 1.0)
    rh = jnp.maximum(y1 - y0, 1e-3 if aligned else 1.0)
    s = sampling_ratio if sampling_ratio > 0 else 2
    # sample grid: [R, oh*s] y coords, [R, ow*s] x coords
    ys = y0[:, None] + rh[:, None] * (
        (jnp.arange(oh * s) + 0.5) / (oh * s))
    xs = x0[:, None] + rw[:, None] * (
        (jnp.arange(ow * s) + 0.5) / (ow * s))

    def bilinear(img, yy, xx):
        yy = jnp.clip(yy, 0, h - 1)
        xx = jnp.clip(xx, 0, w - 1)
        yf = jnp.clip(jnp.floor(yy).astype(jnp.int32), 0, h - 1)
        xf = jnp.clip(jnp.floor(xx).astype(jnp.int32), 0, w - 1)
        yc = jnp.minimum(yf + 1, h - 1)
        xc = jnp.minimum(xf + 1, w - 1)
        wy = yy - yf
        wx = xx - xf
        g = lambda iy, ix: img[:, iy[:, None], ix[None, :]]  # noqa: E731
        val = (g(yf, xf) * ((1 - wy)[:, None] * (1 - wx)[None, :])[None]
               + g(yf, xc) * ((1 - wy)[:, None] * wx[None, :])[None]
               + g(yc, xf) * (wy[:, None] * (1 - wx)[None, :])[None]
               + g(yc, xc) * (wy[:, None] * wx[None, :])[None])
        return val  # [C, oh*s, ow*s]

    def per_roi(bi, yy, xx):
        samp = bilinear(x[bi], yy, xx)  # [C, oh*s, ow*s]
        return samp.reshape(c, oh, s, ow, s).mean(axis=(2, 4))

    return jax.vmap(per_roi)(batch_idx, ys, xs)


def _pair(v):
    return (v, v) if isinstance(v, int) else tuple(v)


def _deform_conv2d_raw(x, offset, weight, bias, mask, stride=1, padding=0,
                       dilation=1, deformable_groups=1, groups=1):
    """Deformable conv v1/v2 as bilinear gather + grouped einsum.

    The reference lowers to the custom ``deformable_conv`` CUDA kernel
    (``operators/deformable_conv_op.cu``); here the sampling grid is dense
    algebra the XLA fuser handles, and the contraction is an MXU einsum.
    x [N,C,H,W]; offset [N, 2*dg*kH*kW, Ho, Wo] as (dy,dx) pairs per tap;
    mask [N, dg*kH*kW, Ho, Wo] or None (v1).
    """
    x = jnp.asarray(x)
    N, C, H, W = x.shape
    Cout, Cpg, kH, kW = weight.shape
    sh, sw = _pair(stride)
    ph, pw = _pair(padding)
    dh, dw = _pair(dilation)
    dg = deformable_groups
    K = kH * kW
    Ho = (H + 2 * ph - (dh * (kH - 1) + 1)) // sh + 1
    Wo = (W + 2 * pw - (dw * (kW - 1) + 1)) // sw + 1
    if C % dg:
        raise InvalidArgumentError(
            "channels %d not divisible by deformable_groups %d" % (C, dg))
    if C % groups:
        raise InvalidArgumentError(
            "channels %d not divisible by groups %d" % (C, groups))

    off = offset.reshape(N, dg, K, 2, Ho, Wo)
    ky = (jnp.arange(kH) * dh).repeat(kW)          # [K]
    kx = jnp.tile(jnp.arange(kW) * dw, kH)         # [K]
    oy = jnp.arange(Ho) * sh - ph                  # [Ho]
    ox = jnp.arange(Wo) * sw - pw                  # [Wo]
    # sampling positions [N, dg, K, Ho, Wo]
    py = ky[None, None, :, None, None] + oy[None, None, None, :, None] \
        + off[:, :, :, 0]
    px = kx[None, None, :, None, None] + ox[None, None, None, None, :] \
        + off[:, :, :, 1]

    Cg = C // dg
    xg = x.reshape(N, dg, Cg, H * W)

    def corner(iy, ix):
        valid = (iy >= 0) & (iy < H) & (ix >= 0) & (ix < W)
        idx = (jnp.clip(iy, 0, H - 1) * W
               + jnp.clip(ix, 0, W - 1)).reshape(N, dg, 1, -1)
        v = jnp.take_along_axis(xg, idx, axis=3)   # [N,dg,Cg,K*Ho*Wo]
        return v * valid.reshape(N, dg, 1, -1).astype(x.dtype)

    y0 = jnp.floor(py).astype(jnp.int32)
    x0 = jnp.floor(px).astype(jnp.int32)
    wy = (py - y0).astype(x.dtype)
    wx = (px - x0).astype(x.dtype)
    wyf = wy.reshape(N, dg, 1, -1)
    wxf = wx.reshape(N, dg, 1, -1)
    sampled = (corner(y0, x0) * (1 - wyf) * (1 - wxf)
               + corner(y0, x0 + 1) * (1 - wyf) * wxf
               + corner(y0 + 1, x0) * wyf * (1 - wxf)
               + corner(y0 + 1, x0 + 1) * wyf * wxf)
    sampled = sampled.reshape(N, dg, Cg, K, Ho, Wo)
    if mask is not None:
        sampled = sampled * mask.reshape(N, dg, 1, K, Ho, Wo).astype(x.dtype)
    sampled = sampled.reshape(N, groups, C // groups, K, Ho, Wo)
    wg = weight.reshape(groups, Cout // groups, Cpg, K)
    out = jnp.einsum("ngckhw,gock->ngohw", sampled, wg,
                     preferred_element_type=x.dtype)
    out = out.reshape(N, Cout, Ho, Wo)
    if bias is not None:
        out = out + bias.reshape(1, Cout, 1, 1)
    return out


from ..framework.dispatch import make_op as _make_op  # noqa: E402
from ..nn.layer.layers import Layer as _Layer  # noqa: E402

deform_conv2d = _make_op(_deform_conv2d_raw, op_name="deform_conv2d")


def read_file(filename, name=None):
    """vision/ops.py:810 parity: file bytes as a uint8 tensor (host op)."""
    from ..framework.tensor import Tensor

    with open(filename, "rb") as f:
        data = f.read()
    return Tensor(jnp.asarray(np.frombuffer(data, np.uint8)),
                  stop_gradient=True)


def decode_jpeg(x, mode: str = "unchanged", name=None):
    """vision/ops.py:855 parity: JPEG bytes → CHW uint8 tensor (host op,
    PIL-backed; the reference uses nvjpeg)."""
    import io as _io

    from PIL import Image

    from ..framework.tensor import Tensor

    raw = bytes(np.asarray(x.value if hasattr(x, "value") else x,
                           np.uint8).tobytes())
    img = Image.open(_io.BytesIO(raw))
    if mode == "gray":
        img = img.convert("L")
    elif mode == "rgb":
        img = img.convert("RGB")
    arr = np.asarray(img)
    if arr.ndim == 2:
        arr = arr[None]
    else:
        arr = arr.transpose(2, 0, 1)
    return Tensor(jnp.asarray(arr), stop_gradient=True)


class DeformConv2D(_Layer):
    """vision/ops.py:621 parity — layer wrapper over deform_conv2d."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, deformable_groups=1, groups=1,
                 weight_attr=None, bias_attr=None):
        super().__init__()
        from ..nn import initializer as I

        kh, kw = _pair(kernel_size)
        self._stride = stride
        self._padding = padding
        self._dilation = dilation
        self._deformable_groups = deformable_groups
        self._groups = groups
        self.weight = self.create_parameter(
            [out_channels, in_channels // groups, kh, kw],
            attr=weight_attr, default_initializer=I.XavierUniform())
        self.bias = self.create_parameter(
            [out_channels], attr=bias_attr, is_bias=True)

    def forward(self, x, offset, mask=None):
        return deform_conv2d(
            x, offset, self.weight, self.bias, mask, stride=self._stride,
            padding=self._padding, dilation=self._dilation,
            deformable_groups=self._deformable_groups, groups=self._groups)


def _bce_logits(logit, target):
    # numerically-stable sigmoid cross entropy
    return jnp.maximum(logit, 0) - logit * target \
        + jnp.log1p(jnp.exp(-jnp.abs(logit)))


def _yolo_loss_raw(x, gt_box, gt_label, gt_score, anchors, anchor_mask,
                   class_num, ignore_thresh, downsample_ratio,
                   use_label_smooth=True, scale_x_y=1.0):
    """YOLOv3 loss (reference vision/ops.py:35 / yolov3_loss_op semantics).

    x [N, A*(5+C), H, W]; gt_box [N, B, 4] normalized cx/cy/w/h;
    gt_label [N, B] int; gt_score [N, B] or None (mixup weights).
    Per-image loss [N].  Static-shape: padded gt slots (w or h == 0) are
    masked, target scatter uses one-hot algebra instead of dynamic writes.
    """
    x = jnp.asarray(x)
    N, _, H, W = x.shape
    A = len(anchor_mask)
    C = int(class_num)
    x = x.reshape(N, A, 5 + C, H, W)
    an_all = jnp.asarray(anchors, jnp.float32).reshape(-1, 2)  # [Atot, 2]
    an_sel = an_all[jnp.asarray(anchor_mask)]                  # [A, 2]
    in_w = float(downsample_ratio * W)
    in_h = float(downsample_ratio * H)

    gt_box = jnp.asarray(gt_box, jnp.float32)
    B = gt_box.shape[1]
    gw, gh = gt_box[..., 2], gt_box[..., 3]
    valid = (gw > 1e-8) & (gh > 1e-8)                          # [N, B]
    score = (jnp.asarray(gt_score, jnp.float32) if gt_score is not None
             else jnp.ones((N, B), jnp.float32))

    # --- responsible anchor per gt: shape-only IoU over ALL anchors ------
    bw = gw[..., None] * in_w                                  # [N,B,1]
    bh = gh[..., None] * in_h
    inter = jnp.minimum(bw, an_all[None, None, :, 0]) \
        * jnp.minimum(bh, an_all[None, None, :, 1])
    union = bw * bh + an_all[None, None, :, 0] * an_all[None, None, :, 1] \
        - inter
    best = jnp.argmax(inter / jnp.maximum(union, 1e-9), axis=-1)  # [N,B]
    mask_arr = jnp.asarray(anchor_mask)
    on_scale = (best[..., None] == mask_arr[None, None, :])    # [N,B,A]
    resp = valid[..., None] & on_scale                         # [N,B,A]
    a_local = jnp.argmax(on_scale, axis=-1)                    # [N,B]

    # --- cell assignment + regression targets ----------------------------
    gi = jnp.clip((gt_box[..., 0] * W).astype(jnp.int32), 0, W - 1)
    gj = jnp.clip((gt_box[..., 1] * H).astype(jnp.int32), 0, H - 1)

    # last-write-wins dedup: if two gts land on the same (anchor, cell),
    # only the later slot keeps the assignment (matches the reference
    # kernel's target scatter, which overwrites)
    resp_any = resp.any(-1)                                    # [N,B]
    key = a_local * (H * W) + gj * W + gi                      # [N,B]
    same = (key[:, :, None] == key[:, None, :]) \
        & resp_any[:, :, None] & resp_any[:, None, :]          # [N,B,B']
    later = jnp.triu(jnp.ones((B, B), bool), k=1)[None]        # b' > b
    kept = resp_any & ~(same & later).any(-1)                  # [N,B]
    resp = resp & kept[..., None]
    t_x = gt_box[..., 0] * W - gi
    t_y = gt_box[..., 1] * H - gj
    p_sel = an_sel[a_local]                                    # [N,B,2]
    t_w = jnp.log(jnp.maximum(gw * in_w, 1e-9) / p_sel[..., 0])
    t_h = jnp.log(jnp.maximum(gh * in_h, 1e-9) / p_sel[..., 1])
    box_w = 2.0 - gw * gh                                      # [N,B]

    # one-hot scatter: cell[n,b] -> [A,H,W] membership of each gt
    cell = (jax.nn.one_hot(gj, H, dtype=jnp.float32)[:, :, :, None]
            * jax.nn.one_hot(gi, W, dtype=jnp.float32)[:, :, None, :])
    sel = resp.astype(jnp.float32)[..., None, None] * cell[:, :, None]
    # sel: [N, B, A, H, W] — 1 where gt b owns anchor a at cell (gj, gi)

    def gather_pred(ch):
        # prediction value at each gt's own cell/anchor: [N, B]
        return jnp.einsum("nbahw,nahw->nb", sel, x[:, :, ch])

    w_pos = box_w * score                                       # [N,B]
    sxy = float(scale_x_y)
    px_l, py_l = gather_pred(0), gather_pred(1)
    if sxy != 1.0:
        # scale_x_y widens the sigmoid: bx = sxy*sig(tx) - 0.5*(sxy-1)
        tx_eff = (t_x + 0.5 * (sxy - 1.0)) / sxy
        ty_eff = (t_y + 0.5 * (sxy - 1.0)) / sxy
    else:
        tx_eff, ty_eff = t_x, t_y
    is_resp = resp.any(-1).astype(jnp.float32)                  # [N,B]
    loss_xy = (_bce_logits(px_l, tx_eff) + _bce_logits(py_l, ty_eff))
    loss_wh = (jnp.abs(gather_pred(2) - t_w) + jnp.abs(gather_pred(3) - t_h))
    loss_box = ((loss_xy + loss_wh) * w_pos * is_resp).sum(-1)  # [N]

    # --- classification ---------------------------------------------------
    smooth_pos = 1.0 - 1.0 / C if (use_label_smooth and C > 1) else 1.0
    smooth_neg = 1.0 / C if (use_label_smooth and C > 1) else 0.0
    cls_t = jax.nn.one_hot(jnp.asarray(gt_label, jnp.int32), C,
                           dtype=jnp.float32)
    cls_t = cls_t * (smooth_pos - smooth_neg) + smooth_neg      # [N,B,C]
    cls_logit = jnp.einsum("nbahw,nachw->nbc", sel, x[:, :, 5:])
    loss_cls = (_bce_logits(cls_logit, cls_t).sum(-1)
                * score * is_resp).sum(-1)

    # --- objectness -------------------------------------------------------
    # predicted boxes for the negative/ignore sweep
    cx = (jnp.arange(W, dtype=jnp.float32) + 0.0)[None, None, None, :]
    cy = (jnp.arange(H, dtype=jnp.float32) + 0.0)[None, None, :, None]
    sig = jax.nn.sigmoid
    bx = (sxy * sig(x[:, :, 0]) - 0.5 * (sxy - 1.0) + cx) / W
    by = (sxy * sig(x[:, :, 1]) - 0.5 * (sxy - 1.0) + cy) / H
    pw = an_sel[:, 0][None, :, None, None] * jnp.exp(x[:, :, 2]) / in_w
    ph = an_sel[:, 1][None, :, None, None] * jnp.exp(x[:, :, 3]) / in_h

    def corners(cxc, cyc, ww, hh):
        return cxc - ww / 2, cyc - hh / 2, cxc + ww / 2, cyc + hh / 2

    px0, py0, px1, py1 = corners(bx, by, pw, ph)                # [N,A,H,W]
    gx0, gy0, gx1, gy1 = corners(gt_box[..., 0], gt_box[..., 1], gw, gh)
    ix0 = jnp.maximum(px0[:, None], gx0[:, :, None, None, None])
    iy0 = jnp.maximum(py0[:, None], gy0[:, :, None, None, None])
    ix1 = jnp.minimum(px1[:, None], gx1[:, :, None, None, None])
    iy1 = jnp.minimum(py1[:, None], gy1[:, :, None, None, None])
    inter2 = jnp.clip(ix1 - ix0, 0) * jnp.clip(iy1 - iy0, 0)    # [N,B,A,H,W]
    area_p = (px1 - px0) * (py1 - py0)
    area_g = ((gx1 - gx0) * (gy1 - gy0))[:, :, None, None, None]
    iou = inter2 / jnp.maximum(area_p[:, None] + area_g - inter2, 1e-9)
    iou = jnp.where(valid[:, :, None, None, None], iou, 0.0)
    ignore = (iou.max(axis=1) > ignore_thresh)                  # [N,A,H,W]

    obj_t = jnp.clip(jnp.einsum("nbahw,nb->nahw", sel, score), 0.0, 1.0)
    obj_pos = jnp.clip(sel.sum(1), 0.0, 1.0)                    # [N,A,H,W]
    obj_l = _bce_logits(x[:, :, 4], obj_t)
    keep = obj_pos + (1.0 - obj_pos) * (1.0 - ignore.astype(jnp.float32))
    loss_obj = (obj_l * keep).sum((1, 2, 3))

    return loss_box + loss_cls + loss_obj


def yolo_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
              ignore_thresh, downsample_ratio, gt_score=None,
              use_label_smooth=True, name=None, scale_x_y=1.0):
    """vision/ops.py:35 parity — see :func:`_yolo_loss_raw`."""
    return _yolo_loss_op(
        x, gt_box, gt_label, gt_score, list(anchors), list(anchor_mask),
        int(class_num), float(ignore_thresh), int(downsample_ratio),
        use_label_smooth=bool(use_label_smooth), scale_x_y=float(scale_x_y))


_yolo_loss_op = _make_op(_yolo_loss_raw, op_name="yolo_loss")
