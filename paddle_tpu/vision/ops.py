"""Detection ops (reference ``python/paddle/vision/ops.py`` +
``fluid/layers/detection.py``: yolo_box, nms/multiclass_nms, box_coder,
box IoU, roi_align).

TPU-native design: everything is static-shape.  NMS — inherently a
sequential suppression — is expressed as a fixed-trip ``lax.scan`` over a
score-sorted candidate list with a suppression mask (no dynamic output
size: callers get ``max_out`` indices + a validity count, the standard XLA
detection formulation).  ``roi_align`` is gather + bilinear weights, which
XLA fuses into a few dense ops rather than the reference's custom CUDA
kernel (``roi_align_op.cu``).
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..core.errors import InvalidArgumentError

__all__ = ["box_iou", "nms", "box_coder", "yolo_box", "roi_align"]


def box_iou(boxes1, boxes2):
    """Pairwise IoU for [N,4] / [M,4] xyxy boxes → [N,M]."""
    b1 = jnp.asarray(boxes1)[:, None, :]
    b2 = jnp.asarray(boxes2)[None, :, :]
    lt = jnp.maximum(b1[..., :2], b2[..., :2])
    rb = jnp.minimum(b1[..., 2:], b2[..., 2:])
    wh = jnp.clip(rb - lt, 0)
    inter = wh[..., 0] * wh[..., 1]
    a1 = (b1[..., 2] - b1[..., 0]) * (b1[..., 3] - b1[..., 1])
    a2 = (b2[..., 2] - b2[..., 0]) * (b2[..., 3] - b2[..., 1])
    return inter / jnp.maximum(a1 + a2 - inter, 1e-9)


def nms(boxes, scores, iou_threshold: float = 0.5,
        max_out: Optional[int] = None,
        score_threshold: Optional[float] = None) -> Tuple:
    """Greedy hard NMS (``nms_op.cc`` semantics, static shapes).

    Returns ``(indices[max_out] int32, count int32)``: the first ``count``
    entries of ``indices`` select kept boxes in descending-score order;
    the tail is padded with -1.  Fixed trip count = max_out scan steps, so
    one compilation serves every input.
    """
    boxes = jnp.asarray(boxes)
    scores = jnp.asarray(scores)
    n = boxes.shape[0]
    if max_out is None:
        max_out = n
    order = jnp.argsort(-scores)
    sorted_boxes = boxes[order]
    iou = box_iou(sorted_boxes, sorted_boxes)
    alive = jnp.ones((n,), bool)
    if score_threshold is not None:
        alive = alive & (scores[order] > score_threshold)

    def body(state, _):
        alive, count, out = state
        # highest-score still-alive candidate (n = none left)
        cand = jnp.argmax(alive)  # first True (argmax of bool)
        any_alive = alive.any()
        out = out.at[count].set(jnp.where(any_alive, order[cand], -1))
        suppress = iou[cand] > iou_threshold
        alive = alive & ~suppress & (jnp.arange(n) != cand)
        alive = jnp.where(any_alive, alive, jnp.zeros_like(alive))
        count = count + jnp.where(any_alive, 1, 0)
        return (alive, count, out), None

    init = (alive, jnp.int32(0), jnp.full((max_out,), -1, jnp.int32))
    (alive, count, out), _ = lax.scan(body, init, None, length=max_out)
    return out, count


def box_coder(prior_box, prior_box_var, target_box,
              code_type: str = "encode_center_size",
              box_normalized: bool = True):
    """box_coder_op.cc parity: encode/decode boxes against priors.

    priors/targets: [N, 4] xyxy.  ``decode_center_size`` treats target_box
    as deltas [N, 4] (dx, dy, dw, dh).
    """
    pb = jnp.asarray(prior_box, jnp.float32)
    pv = jnp.asarray(prior_box_var, jnp.float32)
    tb = jnp.asarray(target_box, jnp.float32)
    norm = 0.0 if box_normalized else 1.0
    pw = pb[:, 2] - pb[:, 0] + norm
    ph = pb[:, 3] - pb[:, 1] + norm
    pcx = pb[:, 0] + pw * 0.5
    pcy = pb[:, 1] + ph * 0.5
    if code_type == "encode_center_size":
        tw = tb[:, 2] - tb[:, 0] + norm
        th = tb[:, 3] - tb[:, 1] + norm
        tcx = tb[:, 0] + tw * 0.5
        tcy = tb[:, 1] + th * 0.5
        out = jnp.stack([(tcx - pcx) / pw, (tcy - pcy) / ph,
                         jnp.log(tw / pw), jnp.log(th / ph)], axis=1)
        return out / pv
    if code_type == "decode_center_size":
        d = tb * pv
        cx = d[:, 0] * pw + pcx
        cy = d[:, 1] * ph + pcy
        w = jnp.exp(d[:, 2]) * pw
        h = jnp.exp(d[:, 3]) * ph
        return jnp.stack([cx - w * 0.5, cy - h * 0.5,
                          cx + w * 0.5 - norm, cy + h * 0.5 - norm], axis=1)
    raise InvalidArgumentError("code_type must be encode/decode_center_size")


def yolo_box(x, img_size, anchors, class_num: int, conf_thresh: float,
             downsample_ratio: int = 32, clip_bbox: bool = True,
             scale_x_y: float = 1.0):
    """yolo_box_op.cc parity: decode one YOLO head.

    ``x``: [N, len(anchors)/2*(5+class_num), H, W]; returns
    (boxes [N, H*W*A, 4] xyxy in image coords, scores [N, H*W*A, classes]).
    Low-confidence boxes get zeroed scores (the reference zeroes the box;
    zero scores is the mask-friendly equivalent for static shapes).
    """
    x = jnp.asarray(x)
    n, c, h, w = x.shape
    na = len(anchors) // 2
    if c != na * (5 + class_num):
        raise InvalidArgumentError(
            "yolo_box channel mismatch: %d != %d*(5+%d)"
            % (c, na, class_num))
    anchors = np.asarray(anchors, np.float32).reshape(na, 2)
    feats = x.reshape(n, na, 5 + class_num, h, w)
    grid_x = jnp.arange(w, dtype=jnp.float32)[None, None, None, :]
    grid_y = jnp.arange(h, dtype=jnp.float32)[None, None, :, None]
    alpha, beta = scale_x_y, -0.5 * (scale_x_y - 1.0)
    bx = (jax.nn.sigmoid(feats[:, :, 0]) * alpha + beta + grid_x) / w
    by = (jax.nn.sigmoid(feats[:, :, 1]) * alpha + beta + grid_y) / h
    input_w = w * downsample_ratio
    input_h = h * downsample_ratio
    bw = jnp.exp(feats[:, :, 2]) * anchors[None, :, 0, None, None] / input_w
    bh = jnp.exp(feats[:, :, 3]) * anchors[None, :, 1, None, None] / input_h
    conf = jax.nn.sigmoid(feats[:, :, 4])
    probs = jax.nn.sigmoid(feats[:, :, 5:]) * conf[:, :, None]
    img_size = jnp.asarray(img_size, jnp.float32)  # [N, 2] (h, w)
    img_h = img_size[:, 0][:, None, None, None]
    img_w = img_size[:, 1][:, None, None, None]
    x0 = (bx - bw * 0.5) * img_w
    y0 = (by - bh * 0.5) * img_h
    x1 = (bx + bw * 0.5) * img_w
    y1 = (by + bh * 0.5) * img_h
    if clip_bbox:
        x0 = jnp.clip(x0, 0, img_w - 1)
        y0 = jnp.clip(y0, 0, img_h - 1)
        x1 = jnp.clip(x1, 0, img_w - 1)
        y1 = jnp.clip(y1, 0, img_h - 1)
    boxes = jnp.stack([x0, y0, x1, y1], axis=-1).reshape(n, -1, 4)
    keep = (conf > conf_thresh)[..., None]
    scores = jnp.where(keep, probs.transpose(0, 1, 3, 4, 2),
                       0.0).reshape(n, -1, class_num)
    return boxes, scores


def roi_align(x, boxes, boxes_num, output_size, spatial_scale: float = 1.0,
              sampling_ratio: int = -1, aligned: bool = True):
    """roi_align_op parity: [N,C,H,W] + [R,4] xyxy rois → [R,C,oh,ow].

    Bilinear sampling as dense gathers; ``boxes_num`` [N] maps each roi to
    its batch image (the LoD replacement, consistent with tensor.segment).
    """
    x = jnp.asarray(x)
    boxes = jnp.asarray(boxes, jnp.float32)
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = output_size
    n, c, h, w = x.shape
    r = boxes.shape[0]
    batch_idx = jnp.repeat(jnp.arange(n), jnp.asarray(boxes_num),
                           total_repeat_length=r)
    offset = 0.5 if aligned else 0.0
    x0 = boxes[:, 0] * spatial_scale - offset
    y0 = boxes[:, 1] * spatial_scale - offset
    x1 = boxes[:, 2] * spatial_scale - offset
    y1 = boxes[:, 3] * spatial_scale - offset
    rw = jnp.maximum(x1 - x0, 1e-3 if aligned else 1.0)
    rh = jnp.maximum(y1 - y0, 1e-3 if aligned else 1.0)
    s = sampling_ratio if sampling_ratio > 0 else 2
    # sample grid: [R, oh*s] y coords, [R, ow*s] x coords
    ys = y0[:, None] + rh[:, None] * (
        (jnp.arange(oh * s) + 0.5) / (oh * s))
    xs = x0[:, None] + rw[:, None] * (
        (jnp.arange(ow * s) + 0.5) / (ow * s))

    def bilinear(img, yy, xx):
        yy = jnp.clip(yy, 0, h - 1)
        xx = jnp.clip(xx, 0, w - 1)
        yf = jnp.clip(jnp.floor(yy).astype(jnp.int32), 0, h - 1)
        xf = jnp.clip(jnp.floor(xx).astype(jnp.int32), 0, w - 1)
        yc = jnp.minimum(yf + 1, h - 1)
        xc = jnp.minimum(xf + 1, w - 1)
        wy = yy - yf
        wx = xx - xf
        g = lambda iy, ix: img[:, iy[:, None], ix[None, :]]  # noqa: E731
        val = (g(yf, xf) * ((1 - wy)[:, None] * (1 - wx)[None, :])[None]
               + g(yf, xc) * ((1 - wy)[:, None] * wx[None, :])[None]
               + g(yc, xf) * (wy[:, None] * (1 - wx)[None, :])[None]
               + g(yc, xc) * (wy[:, None] * wx[None, :])[None])
        return val  # [C, oh*s, ow*s]

    def per_roi(bi, yy, xx):
        samp = bilinear(x[bi], yy, xx)  # [C, oh*s, ow*s]
        return samp.reshape(c, oh, s, ow, s).mean(axis=(2, 4))

    return jax.vmap(per_roi)(batch_idx, ys, xs)
