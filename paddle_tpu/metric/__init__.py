"""``paddle_tpu.metric`` — evaluation metrics.

Reference parity: ``python/paddle/metric/metrics.py`` — ``Metric:47``
(abstract: reset/update/accumulate/name/compute), ``Accuracy:193``,
``Precision:323``, ``Recall:427``, ``Auc:526`` (trapezoid over
threshold-bucket histograms).

Host-side accumulators over numpy (metric state is tiny; device round-trips
happen once per batch on already-computed predictions).
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Union

import numpy as np

from ..core.errors import InvalidArgumentError
from ..framework.tensor import Tensor

__all__ = ["Metric", "Accuracy", "Precision", "Recall", "Auc"]


def _np(x) -> np.ndarray:
    if isinstance(x, Tensor):
        return np.asarray(x.value)
    return np.asarray(x)


class Metric:
    """metrics.py:47 parity."""

    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        raise NotImplementedError

    def compute(self, pred, label, *args):
        """Optional pre-processing on (still-batched) outputs; default
        passthrough (subclasses turn logits into the update()'s input)."""
        return pred, label


class Accuracy(Metric):
    """metrics.py:193 parity: top-k accuracy."""

    def __init__(self, topk: Union[int, Sequence[int]] = (1,), name: Optional[str] = None):
        self.topk = (topk,) if isinstance(topk, int) else tuple(topk)
        self._name_prefix = name or "acc"
        self.maxk = max(self.topk)
        self.reset()

    def compute(self, pred, label, *args):
        pred_np = _np(pred)
        label_np = _np(label)
        if label_np.ndim == pred_np.ndim and label_np.shape[-1] == 1:
            label_np = label_np.squeeze(-1)
        order = np.argsort(-pred_np, axis=-1)[..., : self.maxk]
        correct = order == label_np[..., None]
        return correct

    def update(self, correct, *args):
        correct = _np(correct)
        num = correct.shape[0] if correct.ndim else 1
        for i, k in enumerate(self.topk):
            hits = correct[..., :k].any(axis=-1).sum()
            self.total[i] += float(hits)
        self.count += num
        res = [self.total[i] / max(self.count, 1) for i in range(len(self.topk))]
        return res[0] if len(res) == 1 else res

    def reset(self):
        self.total = [0.0] * len(self.topk)
        self.count = 0

    def accumulate(self):
        res = [t / max(self.count, 1) for t in self.total]
        return res[0] if len(res) == 1 else res

    def name(self):
        if len(self.topk) == 1 and self.topk[0] == 1:
            return [self._name_prefix]
        return ["%s_top%d" % (self._name_prefix, k) for k in self.topk]


class Precision(Metric):
    """metrics.py:323 parity: binary precision (pred > 0.5)."""

    def __init__(self, name: Optional[str] = None):
        self._name = name or "precision"
        self.reset()

    def update(self, preds, labels):
        preds = _np(preds).ravel()
        labels = _np(labels).ravel()
        pos = preds > 0.5
        self.tp += int(np.logical_and(pos, labels == 1).sum())
        self.fp += int(np.logical_and(pos, labels == 0).sum())

    def reset(self):
        self.tp = 0
        self.fp = 0

    def accumulate(self):
        denom = self.tp + self.fp
        return float(self.tp) / denom if denom else 0.0

    def name(self):
        return self._name


class Recall(Metric):
    """metrics.py:427 parity: binary recall (pred > 0.5)."""

    def __init__(self, name: Optional[str] = None):
        self._name = name or "recall"
        self.reset()

    def update(self, preds, labels):
        preds = _np(preds).ravel()
        labels = _np(labels).ravel()
        pos = preds > 0.5
        self.tp += int(np.logical_and(pos, labels == 1).sum())
        self.fn += int(np.logical_and(~pos, labels == 1).sum())

    def reset(self):
        self.tp = 0
        self.fn = 0

    def accumulate(self):
        denom = self.tp + self.fn
        return float(self.tp) / denom if denom else 0.0

    def name(self):
        return self._name


class Auc(Metric):
    """metrics.py:526 parity: ROC AUC via threshold-bucket histograms."""

    def __init__(self, curve: str = "ROC", num_thresholds: int = 4095,
                 name: Optional[str] = None):
        if curve != "ROC":
            raise InvalidArgumentError("only ROC AUC is supported, got %r" % curve)
        self.num_thresholds = int(num_thresholds)
        self._name = name or "auc"
        self.reset()

    def update(self, preds, labels):
        preds = _np(preds)
        labels = _np(labels).ravel()
        if preds.ndim == 2 and preds.shape[1] == 2:
            preds = preds[:, 1]  # probability of the positive class
        preds = preds.ravel()
        buckets = np.clip(
            (preds * self.num_thresholds).astype(np.int64), 0,
            self.num_thresholds)
        np.add.at(self._stat_pos, buckets[labels == 1], 1)
        np.add.at(self._stat_neg, buckets[labels != 1], 1)

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds + 1, np.int64)
        self._stat_neg = np.zeros(self.num_thresholds + 1, np.int64)

    def accumulate(self):
        # trapezoid over buckets from high threshold to low
        tot_pos = tot_neg = 0.0
        area = 0.0
        for i in range(self.num_thresholds, -1, -1):
            new_pos = tot_pos + self._stat_pos[i]
            new_neg = tot_neg + self._stat_neg[i]
            area += (new_neg - tot_neg) * (tot_pos + new_pos) / 2.0
            tot_pos, tot_neg = new_pos, new_neg
        if tot_pos == 0 or tot_neg == 0:
            return 0.0
        return float(area / (tot_pos * tot_neg))

    def name(self):
        return self._name


def accuracy(input, label, k: int = 1, correct=None, total=None, name=None):
    """paddle.metric.accuracy functional parity: top-k accuracy scalar."""
    import jax.numpy as jnp

    from ..framework.dispatch import make_op

    def _raw(pred, lab):
        topk = jnp.argsort(-pred, axis=-1)[..., :k]
        lab2 = jnp.asarray(lab).reshape(-1, 1)
        hit = (topk == lab2).any(axis=-1)
        return hit.astype(jnp.float32).mean()

    return make_op(_raw, differentiable=False, op_name="metric_accuracy")(
        input, label)
