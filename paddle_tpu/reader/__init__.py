"""Legacy reader-decorator API (``paddle.reader``).

Composable generator transforms over *reader creators* — zero-arg
callables returning an iterable of samples. This is the fluid-era data
API (reference ``python/paddle/reader/decorator.py:52-640``); the modern
path is ``paddle_tpu.io.DataLoader``, which adds multiprocess workers and
async device staging. These decorators are host-side pure Python, so the
TPU story is unchanged: they feed the same numpy batches the DataLoader
stages onto the chip.
"""
from __future__ import annotations

import itertools
import queue
import random as _random
import threading
from itertools import zip_longest

__all__ = [
    "cache", "map_readers", "shuffle", "chain", "compose", "buffered",
    "firstn", "xmap_readers", "multiprocess_reader", "ComposeNotAligned",
]


class ComposeNotAligned(ValueError):
    """Raised by :func:`compose` when input readers have unequal length."""


class _RaisedInWorker:
    """Queue envelope carrying a worker thread's exception to the consumer."""

    def __init__(self, error):
        self.error = error


def cache(reader):
    """Cache the first COMPLETE pass in memory; later passes replay it.

    Each running pass fills its own local buffer and commits only on
    completion, so an abandoned pass (early break, firstn) or two
    interleaved iterations (the same cached reader zipped with itself)
    can never memoize duplicated or dropped samples.
    Reference: ``reader/decorator.py:52``.
    """
    memory = []
    filled = []

    def cached():
        if filled:
            yield from memory
            return
        local = []
        for item in reader():
            local.append(item)
            yield item
        if not filled:  # first COMPLETE pass wins
            memory[:] = local
            filled.append(True)

    return cached


def map_readers(func, *readers):
    """Apply ``func`` element-wise across the zipped outputs of ``readers``.

    Reference: ``reader/decorator.py:92``.
    """

    def mapped():
        for items in zip(*[r() for r in readers]):
            yield func(*items)

    return mapped


def shuffle(reader, buf_size):
    """Locally shuffle samples within a sliding buffer of ``buf_size``.

    Reference: ``reader/decorator.py:134``.
    """

    def shuffled():
        buf = []
        for item in reader():
            buf.append(item)
            if len(buf) >= buf_size:
                _random.shuffle(buf)
                yield from buf
                buf = []
        if buf:
            _random.shuffle(buf)
            yield from buf

    return shuffled


def chain(*readers):
    """Concatenate readers back to back.

    Reference: ``reader/decorator.py:183``.
    """

    def chained():
        yield from itertools.chain(*[r() for r in readers])

    return chained


def compose(*readers, **kwargs):
    """Zip readers into flat tuples: ``(1, 2), 3 -> (1, 2, 3)``.

    ``check_alignment=True`` (default) raises :class:`ComposeNotAligned`
    when the readers have different lengths; ``False`` truncates to the
    shortest. Reference: ``reader/decorator.py:248``.
    """
    check_alignment = kwargs.pop("check_alignment", True)
    if kwargs:
        raise TypeError("compose() got unexpected kwargs %s" % sorted(kwargs))

    def as_tuple(x):
        return x if isinstance(x, tuple) else (x,)

    def composed():
        its = [r() for r in readers]
        if check_alignment:
            for outputs in zip_longest(*its):
                if any(o is None for o in outputs):
                    raise ComposeNotAligned(
                        "outputs of readers are not aligned.")
                yield sum((as_tuple(o) for o in outputs), ())
        else:
            for outputs in zip(*its):
                yield sum((as_tuple(o) for o in outputs), ())

    return composed


def buffered(reader, size):
    """Read ahead into a bounded buffer on a background thread.

    Reference: ``reader/decorator.py:308`` (the reference's C++
    buffered_reader analog for this legacy API; the DataLoader's
    prefetch supersedes it on the modern path).
    """

    def buffered_reader():
        q = queue.Queue(maxsize=size)
        end = object()

        def fill():
            # a reader failure is forwarded and re-raised in the consumer
            # — NOT swallowed into a silently truncated epoch
            try:
                for item in reader():
                    q.put(item)
                q.put(end)
            except BaseException as e:  # noqa: BLE001
                q.put(_RaisedInWorker(e))

        t = threading.Thread(target=fill, daemon=True)
        t.start()
        while True:
            item = q.get()
            if item is end:
                break
            if isinstance(item, _RaisedInWorker):
                raise item.error
            yield item

    return buffered_reader


def firstn(reader, n):
    """Limit the reader to its first ``n`` samples.

    Reference: ``reader/decorator.py:367``.
    """

    def firstn_reader():
        yield from itertools.islice(reader(), n)

    return firstn_reader


def xmap_readers(mapper, reader, process_num, buffer_size, order=False):
    """Apply ``mapper`` over ``reader`` with ``process_num`` worker threads.

    ``order=True`` preserves input order (workers tag samples with their
    index and a reorder stage releases them sequentially).
    Reference: ``reader/decorator.py:412``.
    """

    def xreader():
        in_q = queue.Queue(maxsize=buffer_size)
        out_q = queue.Queue(maxsize=buffer_size)
        end = object()

        def feed():
            # end markers go out even when the source reader raises, or
            # every worker (and the consumer) would block forever; the
            # exception itself is forwarded and re-raised in the consumer
            try:
                for i, item in enumerate(reader()):
                    in_q.put((i, item))
            except BaseException as e:  # noqa: BLE001
                out_q.put(_RaisedInWorker(e))
            finally:
                for _ in range(process_num):
                    in_q.put(end)

        def work():
            try:
                while True:
                    got = in_q.get()
                    if got is end:
                        return
                    i, item = got
                    out_q.put((i, mapper(item)))
            except BaseException as e:  # noqa: BLE001
                out_q.put(_RaisedInWorker(e))
            finally:
                out_q.put(end)

        threads = [threading.Thread(target=feed, daemon=True)]
        threads += [threading.Thread(target=work, daemon=True)
                    for _ in range(process_num)]
        for t in threads:
            t.start()

        finished = 0
        pending, nxt = {}, 0
        while finished < process_num:
            got = out_q.get()
            if got is end:
                finished += 1
                continue
            if isinstance(got, _RaisedInWorker):
                raise got.error
            i, mapped = got
            if order:
                pending[i] = mapped
                while nxt in pending:
                    yield pending.pop(nxt)
                    nxt += 1
            else:
                yield mapped
        for i in sorted(pending):
            yield pending[i]

    return xreader


def multiprocess_reader(readers, use_pipe=True, queue_size=1000):
    """Interleave multiple readers, each driven from its own process.

    The reference forks one OS process per reader and merges via a pipe
    or queue (``reader/decorator.py:505``). Here each reader runs on its
    own *thread* feeding one bounded queue: the heavy lifting in this
    framework's data path (decode/augment) is numpy releasing the GIL,
    and true multiprocess loading lives in ``paddle_tpu.io.DataLoader``
    (shared-memory workers), which this legacy shim intentionally does
    not duplicate. Semantics (interleaved, unordered merge; all readers
    exhausted) match the reference.
    """
    if not readers:
        raise ValueError("multiprocess_reader: need at least one reader")

    def merged():
        q = queue.Queue(maxsize=queue_size)
        end = object()

        def drive(r):
            # forward a failed reader's exception instead of silently
            # dropping its share of the data
            try:
                for item in r():
                    q.put(item)
                q.put(end)
            except BaseException as e:  # noqa: BLE001
                q.put(_RaisedInWorker(e))

        for r in readers:
            threading.Thread(target=drive, args=(r,), daemon=True).start()
        finished = 0
        while finished < len(readers):
            item = q.get()
            if item is end:
                finished += 1
                continue
            if isinstance(item, _RaisedInWorker):
                raise item.error
            yield item

    return merged
