"""``paddle.hub`` namespace — re-exports the hapi hub implementation
(mirrors the reference layout: ``python/paddle/hub.py`` → ``hapi/hub.py``).
"""
from .hapi.hub import help, list, load  # noqa: F401,A004

__all__ = ["list", "help", "load"]
