/* C inference API for paddle_tpu (capi_exp analog).
 *
 * Link against libpaddle_tpu_c.so (paddle_tpu.capi.build() compiles it;
 * paddle_tpu.sysconfig.get_lib() returns its directory). The library
 * embeds a CPython interpreter running the paddle_tpu runtime; all entry
 * points are GIL-guarded and safe to call from a single host thread.
 *
 * Reference surface: paddle/fluid/inference/capi_exp/pd_inference_api.h
 * (Config/Predictor verticals; this header is the TPU-native reduction).
 */
#ifndef PADDLE_TPU_C_H_
#define PADDLE_TPU_C_H_

#ifdef __cplusplus
extern "C" {
#endif

/* Start the embedded runtime. extra_sys_paths: ':'-separated directories
 * prepended to sys.path (pass the repo root when running from a source
 * tree), or NULL. Returns 0 on success. */
int PD_Init(const char* extra_sys_paths);

/* Version string of the C API (static storage; do not free). */
const char* PD_GetVersion(void);

/* Load a saved StableHLO inference artifact (paddle_tpu.jit.save prefix).
 * Returns an opaque predictor handle, or NULL on failure. */
void* PD_PredictorCreate(const char* model_prefix);

/* Run the predictor on a float32 input of the given shape.
 *   data/shape/ndim:     input buffer and its dimensions
 *   out/out_capacity:    caller-allocated output buffer (element count)
 *   out_shape/out_ndim:  receive the output dimensions
 * Returns 0 on success (output in out/out_shape); a POSITIVE value is
 * the required out_capacity (grow the buffer and retry); negative is an
 * error (-1 bad handle, -2..-8 runtime errors, details on stderr). */
long long PD_PredictorRunFloat(void* handle, const float* data,
                               const long long* shape, int ndim, float* out,
                               long long out_capacity, long long* out_shape,
                               int* out_ndim);

/* Release a predictor handle. */
void PD_PredictorDestroy(void* handle);

/* Shut down the embedded runtime. PD_Init afterwards is unsupported. */
void PD_Finalize(void);

#ifdef __cplusplus
}  /* extern "C" */
#endif

#endif  /* PADDLE_TPU_C_H_ */
