"""Thread-local autocast state, read by the op dispatcher.

Reference parity: the C++ global ``AmpOperators`` + tracer amp level
(``imperative/amp_auto_cast.cc:`` GetCurrentTracer->AMPLevel, allow/block
op sets).  Lives in ``core`` so ``framework.dispatch`` can consult it
without importing the user-facing ``paddle_tpu.amp`` package (no cycle).
"""
from __future__ import annotations

import threading
from typing import Optional, Set

_tls = threading.local()


class AmpAttrs:
    __slots__ = ("enabled", "dtype", "white", "black", "level")

    def __init__(self, enabled=False, dtype="bfloat16",
                 white: Optional[Set[str]] = None,
                 black: Optional[Set[str]] = None, level: str = "O1"):
        self.enabled = enabled
        self.dtype = dtype
        self.white = white or set()
        self.black = black or set()
        self.level = level


_DISABLED = AmpAttrs()


def current() -> AmpAttrs:
    return getattr(_tls, "state", _DISABLED)


def push(state: AmpAttrs) -> AmpAttrs:
    prev = current()
    _tls.state = state
    return prev


def pop(prev: AmpAttrs) -> None:
    _tls.state = prev


def amp_enabled() -> bool:
    return current().enabled
