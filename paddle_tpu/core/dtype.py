"""Dtype registry and default-dtype policy.

Reference parity: ``framework/data_type.h`` proto enum + ``paddle.set_default_dtype``.
TPU-first deltas: bfloat16 is a first-class citizen (MXU native), float64 is
discouraged (soft-emulated on TPU) but supported for CPU-mesh tests.
"""
from __future__ import annotations

from typing import Any

import jax.numpy as jnp
import numpy as np

bool_ = jnp.bool_
uint8 = jnp.uint8
int8 = jnp.int8
int16 = jnp.int16
int32 = jnp.int32
int64 = jnp.int64
float16 = jnp.float16
bfloat16 = jnp.bfloat16
float32 = jnp.float32
float64 = jnp.float64
complex64 = jnp.complex64
complex128 = jnp.complex128

_ALIASES = {
    "bool": bool_,
    "uint8": uint8,
    "int8": int8,
    "int16": int16,
    "int32": int32,
    "int64": int64,
    "float16": float16,
    "fp16": float16,
    "bfloat16": bfloat16,
    "bf16": bfloat16,
    "float32": float32,
    "fp32": float32,
    "float64": float64,
    "fp64": float64,
    "double": float64,
    "complex64": complex64,
    "complex128": complex128,
}

_default_dtype = float32


def convert_dtype(dtype: Any):
    """Normalize a user-provided dtype spec to a numpy/jnp dtype class."""
    if dtype is None:
        return None
    if isinstance(dtype, str):
        key = dtype.lower().replace("paddle.", "")
        if key in _ALIASES:
            return _ALIASES[key]
        raise ValueError(f"Unknown dtype string: {dtype!r}")
    return np.dtype(dtype).type if not hasattr(dtype, "dtype") else dtype


def set_default_dtype(d: Any) -> None:
    """paddle.set_default_dtype parity; only float kinds allowed."""
    global _default_dtype
    d = convert_dtype(d)
    if np.dtype(d).kind not in "f" and d is not bfloat16:
        raise TypeError(f"default dtype must be floating, got {d}")
    _default_dtype = d


def get_default_dtype():
    """The default float dtype as its canonical STRING name ('float32'),
    matching the reference (`framework.py:69` returns the string form);
    ported code compares it against 'float32'/'float64' literals. The
    string is a valid dtype argument everywhere jnp/numpy take one."""
    return np.dtype(_default_dtype).name


def _x64_enabled() -> bool:
    import jax

    return bool(jax.config.jax_enable_x64)


def canonical_index_dtype():
    """Paddle's index dtype is int64; TPUs (and x64-disabled JAX) want int32.

    All index-producing ops (argmax/topk/randint...) route through this so the
    framework is int32-first on TPU while staying int64 when x64 is enabled.
    """
    return int64 if _x64_enabled() else int32


def canonicalize(dtype: Any):
    """Map a requested dtype to what this runtime actually supports (x64 policy)."""
    d = convert_dtype(dtype)
    if d is None:
        return None
    if not _x64_enabled():
        if np.dtype(d) in (np.dtype("int64"), np.dtype("uint64")):
            return int32
        if np.dtype(d) == np.dtype("float64"):
            return float32
    return d


def is_floating(dtype: Any) -> bool:
    dtype = jnp.dtype(dtype)
    return jnp.issubdtype(dtype, jnp.floating)


def is_integer(dtype: Any) -> bool:
    return jnp.issubdtype(jnp.dtype(dtype), jnp.integer)


def finfo(dtype):
    return jnp.finfo(dtype)


def iinfo(dtype):
    return jnp.iinfo(dtype)
