"""Seed / PRNG policy: global-seed facade over explicit JAX keys.

Reference parity: ``paddle.seed`` + per-device ``framework/generator.cc``
Generators.  JAX randomness is explicit-key; the facade keeps paddle's
stateful-looking API while staying trace-safe:

- Eager: a process-global :class:`Generator` folds a monotonically increasing
  counter into its root key — every eager random op gets a fresh key.
- Under ``jit``/``to_static``: folding a *constant* key inside a trace would
  freeze randomness across calls, so the jit wrappers install a **traced** key
  for the duration of the trace via :func:`rng_guard`; ``next_key`` derives
  from it instead.  The wrapper passes a fresh key argument per call, so
  compiled executables see new randomness without retracing.
"""
from __future__ import annotations

import contextlib
import threading
from typing import List, Optional

import jax


class Generator:
    """Stateful key source (framework/generator.cc analog)."""

    def __init__(self, seed: int = 0):
        # key creation is deferred: building a jax key initializes the XLA
        # backend, and `import paddle_tpu` must stay backend-free (the
        # launcher parent, spawn children pre-rendezvous, and CLI tools all
        # import the package before choosing a platform)
        self._seed = seed
        self._key_cache: Optional[jax.Array] = None
        self._counter = 0
        self._lock = threading.Lock()

    @property
    def _key(self) -> jax.Array:
        # the lazy build is shared mutable state: unguarded, two
        # threads could interleave with a concurrent manual_seed and
        # publish a key for the OLD seed after the reseed "completed"
        # (tools/analysis lock-discipline).  No caller holds the lock
        # while reading the property (next_key's critical section ends
        # before the fold_in), so taking it here cannot deadlock.
        with self._lock:
            if self._key_cache is None:
                self._key_cache = jax.random.key(self._seed)
            return self._key_cache

    def manual_seed(self, seed: int) -> "Generator":
        with self._lock:
            self._seed = seed
            self._key_cache = jax.random.key(seed)
            self._counter = 0
        return self

    def initial_seed(self) -> int:
        return self._seed

    def next_key(self) -> jax.Array:
        traced = _current_traced_key()
        with self._lock:
            self._counter += 1
            counter = self._counter
        if traced is not None:
            return jax.random.fold_in(traced, counter)
        return jax.random.fold_in(self._key, counter)

    def split(self, n: int) -> jax.Array:
        return jax.random.split(self.next_key(), n)

    def get_state(self):
        return {"seed": self._seed, "counter": self._counter}

    def set_state(self, state) -> None:
        with self._lock:
            self._seed = state["seed"]
            self._key_cache = jax.random.key(state["seed"])
            self._counter = state["counter"]


default_generator = Generator(0)

_tls = threading.local()


def _key_stack() -> List[jax.Array]:
    if not hasattr(_tls, "stack"):
        _tls.stack = []
    return _tls.stack


def _current_traced_key() -> Optional[jax.Array]:
    stack = _key_stack()
    return stack[-1] if stack else None


@contextlib.contextmanager
def rng_guard(key: jax.Array):
    """Install a (possibly traced) key as the randomness source for this thread.

    Used by ``jit.to_static`` so stateful-looking random ops inside the traced
    function derive from a per-call key argument.
    """
    stack = _key_stack()
    stack.append(key)
    try:
        yield
    finally:
        stack.pop()


def seed(value: int) -> Generator:
    """paddle.seed parity: reseed the global generator."""
    return default_generator.manual_seed(value)


def next_key() -> jax.Array:
    return default_generator.next_key()


def split_key(n: int) -> jax.Array:
    return default_generator.split(n)


def get_rng_state():
    return default_generator.get_state()


def set_rng_state(state) -> None:
    default_generator.set_state(state)


def get_cuda_rng_state():  # API-parity alias; single generator on TPU
    return get_rng_state()


def set_cuda_rng_state(state) -> None:
    set_rng_state(state)


@contextlib.contextmanager
def replay_counter(counter: int):
    """Pin the generator's fold-in counter for a deterministic replay.

    ``create_graph`` re-executes recorded primal functions at backward time
    (engine.py); random ops inside them must re-draw the SAME keys they drew
    at forward time, and the replay must not advance the global stream."""
    save = default_generator._counter
    default_generator._counter = counter
    try:
        yield
    finally:
        default_generator._counter = save
