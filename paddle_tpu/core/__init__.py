"""Core substrate: device identity, dtypes, flags, errors, RNG policy.

TPU-native replacement for the reference's L0 platform layer
(``paddle/fluid/platform/``): ``Place``/``DeviceContext`` collapse onto
``jax.Device``; streams/handles/allocators are owned by XLA.  What survives is
the *identity* API (``set_device``/``get_device``), the flag registry, the
enforce-style error discipline, and the seed/PRNG policy.
"""
from . import device, dtype, errors, flags, random  # noqa: F401
from .device import (  # noqa: F401
    CPUPlace,
    CUDAPlace,
    Place,
    TPUPlace,
    get_device,
    is_compiled_with_cuda,
    is_compiled_with_tpu,
    set_device,
)
from .dtype import (  # noqa: F401
    bfloat16,
    bool_,
    complex64,
    complex128,
    float16,
    float32,
    float64,
    get_default_dtype,
    int8,
    int16,
    int32,
    int64,
    set_default_dtype,
    uint8,
)
from .errors import EnforceNotMet, InvalidArgumentError, enforce, raise_unimplemented  # noqa: F401
from .flags import get_flags, set_flags  # noqa: F401
from .random import Generator, default_generator, get_rng_state, seed, set_rng_state  # noqa: F401
