"""Global runtime flag registry.

Reference parity: gflags in ``platform/flags.cc`` surfaced through
``paddle.set_flags/get_flags`` (``fluid/framework.py:5863,5886``) with
``FLAGS_*`` env-var pass-through parsed at init (``platform/init.cc``).

TPU mapping: most reference flags (memory fractions, cudnn workspace) are
XLA's job; the ones that survive are debug/determinism/logging toggles plus
XLA knobs we forward via ``jax.config`` / ``XLA_FLAGS``.
"""
from __future__ import annotations

import os
import threading
from typing import Any, Callable, Dict, Iterable, Optional

_lock = threading.Lock()


class _Flag:
    __slots__ = ("name", "value", "default", "help", "on_set")

    def __init__(self, name: str, default: Any, help: str, on_set: Optional[Callable[[Any], None]] = None):
        self.name = name
        self.default = default
        self.value = default
        self.help = help
        self.on_set = on_set


_REGISTRY: Dict[str, _Flag] = {}


def define_flag(name: str, default: Any, help: str = "", on_set: Optional[Callable[[Any], None]] = None) -> None:
    with _lock:
        if name in _REGISTRY:
            raise KeyError(f"flag {name} already defined")
        flag = _Flag(name, default, help, on_set)
        _REGISTRY[name] = flag
    env = os.environ.get(name)  # FLAGS_* env pass-through (platform/init.cc parity)
    if env is not None:
        set_flags({name: _parse(env, default)})


def _parse(text: str, default: Any) -> Any:
    if isinstance(default, bool):
        return text.lower() in ("1", "true", "yes", "on")
    if isinstance(default, int):
        return int(text)
    if isinstance(default, float):
        return float(text)
    return text


def set_flags(flags: Dict[str, Any]) -> None:
    """paddle.set_flags parity."""
    for name, value in flags.items():
        with _lock:
            flag = _REGISTRY.get(name)
            if flag is None:
                raise KeyError(f"unknown flag {name}; defined: {sorted(_REGISTRY)}")
            flag.value = value
        if flag.on_set is not None:
            flag.on_set(value)


def get_flags(flags: Iterable[str] | str | None = None) -> Dict[str, Any]:
    """paddle.get_flags parity; None returns all flags."""
    with _lock:
        if flags is None:
            return {k: f.value for k, f in _REGISTRY.items()}
        if isinstance(flags, str):
            flags = [flags]
        return {name: _REGISTRY[name].value for name in flags}


def flag(name: str) -> Any:
    return _REGISTRY[name].value


# --- core flags (subset of platform/flags.cc that makes sense on TPU) ---
define_flag("FLAGS_check_nan_inf", False, "scan outputs of each jitted step for nan/inf (debug)")
define_flag("FLAGS_benchmark", False, "block on each step for accurate timing")
define_flag("FLAGS_deterministic", True, "prefer deterministic XLA reductions")
define_flag("FLAGS_log_level", 0, "verbosity for paddle_tpu host-side logging (GLOG_v analog)")
define_flag("FLAGS_use_donated_buffers", True, "donate param/opt-state buffers into jitted train steps")
define_flag("FLAGS_prefetch_depth", 2, "device prefetch depth for DataLoader double buffering")
define_flag("FLAGS_amp_dtype", "bfloat16", "autocast compute dtype (bfloat16|float16)")
define_flag("FLAGS_jit_cache", True, "reuse compiled executables across to_static calls")
define_flag("FLAGS_seq_block_size", 512, "ring/flash attention block length on the sequence axis")
define_flag("FLAGS_eager_mode", True, "ops execute eagerly (dygraph) when not inside jit")
