"""Enforce-style error discipline with Python-frame attribution.

Reference parity: ``PADDLE_ENFORCE*`` / ``PADDLE_THROW`` (``platform/enforce.h:415-510``)
and the op-call-stack attribution that maps C++ failures back to the Python line
that created the op (``framework/op_call_stack.cc``).  In a JAX-native design
errors mostly surface from tracing (good Python tracebacks already); what we add
is a typed error taxonomy matching the reference's ``error_codes.proto`` and an
``enforce`` helper that annotates shape/dtype checks with the calling layer.
"""
from __future__ import annotations

import traceback
from typing import Any, NoReturn, Optional


class EnforceNotMet(RuntimeError):
    """Base error; carries an error-code name like the reference proto."""

    code = "LEGACY"

    def __init__(self, message: str, hint: Optional[str] = None):
        self.raw_message = message
        self.hint = hint
        full = f"[{self.code}] {message}"
        if hint:
            full += f"\n  [Hint: {hint}]"
        # FLAGS_log_level >= 1 → append the creating Python frames
        # (op_call_stack.cc attribution; SURVEY §5.5)
        try:
            from .flags import flag as _flag

            if _flag("FLAGS_log_level") >= 1:
                full += "\n  [Python call stack]\n" + current_python_callstack()
        except Exception:  # flags not registered yet during bootstrap
            pass
        super().__init__(full)


class InvalidArgumentError(EnforceNotMet):
    code = "INVALID_ARGUMENT"


class NotFoundError(EnforceNotMet):
    code = "NOT_FOUND"


class OutOfRangeError(EnforceNotMet):
    code = "OUT_OF_RANGE"


class AlreadyExistsError(EnforceNotMet):
    code = "ALREADY_EXISTS"


class PreconditionNotMetError(EnforceNotMet):
    code = "PRECONDITION_NOT_MET"


class PermissionDeniedError(EnforceNotMet):
    code = "PERMISSION_DENIED"


class ExecutionTimeoutError(EnforceNotMet):
    code = "EXECUTION_TIMEOUT"


class UnimplementedError(EnforceNotMet):
    code = "UNIMPLEMENTED"


class UnavailableError(EnforceNotMet):
    code = "UNAVAILABLE"


class FatalError(EnforceNotMet):
    code = "FATAL"


class ExternalError(EnforceNotMet):
    code = "EXTERNAL"


def enforce(cond: Any, message: str, exc: type = InvalidArgumentError, hint: Optional[str] = None) -> None:
    """PADDLE_ENFORCE analog: raise ``exc`` with message when ``cond`` is falsy.

    Never call on traced values — this is a host-side (trace-time) check.
    """
    if not cond:
        raise exc(message, hint=hint)


def enforce_eq(a: Any, b: Any, what: str = "value") -> None:
    if a != b:
        raise InvalidArgumentError(f"expected {what} == {b!r}, got {a!r}")


def enforce_shape(x: Any, expected: tuple, what: str = "tensor") -> None:
    shape = tuple(x.shape)
    if len(shape) != len(expected) or any(e not in (-1, None, s) for s, e in zip(shape, expected)):
        raise InvalidArgumentError(f"{what} shape mismatch: expected {expected}, got {shape}")


def raise_unimplemented(feature: str) -> NoReturn:
    raise UnimplementedError(
        f"{feature} is not implemented in paddle_tpu yet",
        hint="see SURVEY.md component inventory for the build plan",
    )


def current_python_callstack(limit: int = 8) -> str:
    """op_call_stack.cc analog: capture the creating Python frames for a layer/op."""
    return "".join(traceback.format_stack(limit=limit)[:-1])
