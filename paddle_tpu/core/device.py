"""Device identity and discovery.

Reference parity: ``platform::Place`` variants (``platform/place.h:24-94``) and
``paddle.set_device/get_device`` (``python/paddle/device.py:181,208``).  On TPU
a "place" is just a ``jax.Device``; the per-device stream/handle bundle
(``platform/device_context.h``) has no equivalent because XLA owns scheduling.

Design: we keep a tiny tagged ``Place`` for API compatibility, backed by the
live ``jax.Device``.  ``set_device`` selects the default backend for eager ops
via ``jax.default_device``; under ``jit`` placement is controlled by shardings,
not places.
"""
from __future__ import annotations

import dataclasses
import functools
import os
from typing import Optional

import jax


@dataclasses.dataclass(frozen=True)
class Place:
    """Device identity: backend kind + index (platform/place.h analog)."""

    kind: str  # "cpu" | "tpu" | "gpu"
    index: int = 0

    def jax_device(self) -> jax.Device:
        devs = jax.devices(self.kind) if self.kind != "cpu" else jax.devices("cpu")
        if self.index >= len(devs):
            from .errors import InvalidArgumentError

            raise InvalidArgumentError(
                f"Place {self} out of range: only {len(devs)} {self.kind} device(s) visible"
            )
        return devs[self.index]

    def __repr__(self) -> str:  # paddle prints e.g. CUDAPlace(0)
        return f"{self.kind.upper()}Place({self.index})"


def CPUPlace(index: int = 0) -> Place:
    return Place("cpu", index)


def TPUPlace(index: int = 0) -> Place:
    return Place("tpu", index)


def CUDAPlace(index: int = 0) -> Place:  # accepted for API parity; maps to gpu backend
    return Place("gpu", index)


_current_place: Optional[Place] = None
_default_device_ctx = None


def _backend_available(kind: str) -> bool:
    try:
        return len(jax.devices(kind)) > 0
    except RuntimeError:
        return False


@functools.lru_cache(maxsize=None)
def _auto_backend() -> str:
    for kind in ("tpu", "gpu", "cpu"):
        if _backend_available(kind):
            return kind
    return "cpu"


def set_device(device: str | Place) -> Place:
    """Select the default device for eager execution.

    Accepts ``"tpu"``, ``"cpu"``, ``"gpu:0"``, ``"tpu:3"`` or a :class:`Place`.
    Mirrors ``paddle.set_device`` (``python/paddle/device.py:181``): this is the
    north-star hook point — ``set_device('tpu')`` makes every subsequent eager
    op and jit compile target TPU.
    """
    global _current_place, _default_device_ctx
    if isinstance(device, str):
        kind, _, idx = device.partition(":")
        kind = {"cuda": "gpu", "xpu": "tpu", "npu": "tpu"}.get(kind, kind)
        place = Place(kind, int(idx) if idx else 0)
    else:
        place = device
    dev = place.jax_device()  # validates
    # jax.default_device is a context manager/config; use the config setter so it
    # applies process-wide like paddle's global place.
    if _default_device_ctx is not None:
        _default_device_ctx.__exit__(None, None, None)
    _default_device_ctx = jax.default_device(dev)
    _default_device_ctx.__enter__()
    _current_place = place
    return place


def get_device() -> str:
    """Return current device string, e.g. ``"tpu:0"`` (device.py:208 parity)."""
    if _current_place is None:
        return f"{_auto_backend()}:0"
    return f"{_current_place.kind}:{_current_place.index}"


def current_place() -> Place:
    if _current_place is None:
        return Place(_auto_backend(), 0)
    return _current_place


def device_count(kind: Optional[str] = None) -> int:
    kind = kind or _auto_backend()
    return len(jax.devices(kind)) if _backend_available(kind) else 0


def is_compiled_with_cuda() -> bool:  # fluid/framework.py:392 parity
    return _backend_available("gpu")


def is_compiled_with_tpu() -> bool:
    return _backend_available("tpu")


def XPUPlace(index: int = 0) -> Place:  # vendor alias for API parity
    return Place(_auto_backend(), index)


def local_device_count() -> int:
    return jax.local_device_count()


def global_device_count() -> int:
    return jax.device_count()


def synchronize() -> None:
    """Block until all pending device work completes (dev_ctx->Wait parity).

    Waits on every live jax.Array — unlike enqueueing a fresh trivial op, this
    actually orders against previously dispatched async work.
    """
    for arr in jax.live_arrays():
        try:
            arr.block_until_ready()
        except RuntimeError:
            pass  # deleted/donated buffers


def env_device_override() -> Optional[str]:
    return os.environ.get("PADDLE_TPU_DEVICE")


def CUDAPinnedPlace(index: int = 0) -> Place:
    """API parity: pinned host memory maps to the CPU backend on TPU hosts
    (the prefetch path stages through ordinary host RAM + device_put)."""
    return Place("cpu", index)


def NPUPlace(index: int = 0) -> Place:
    """API parity for the reference's Ascend backend: no NPU on this
    platform; resolves to the accelerator if present, else CPU."""
    return Place("tpu" if _backend_available("tpu") else "cpu", index)
