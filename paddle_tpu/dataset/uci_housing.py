"""Legacy UCI housing readers (``paddle.dataset.uci_housing``).

Reference: ``python/paddle/dataset/uci_housing.py:69-135``. Samples are
(13 mean-centered range-normalized float features, [price]); the split is
the reference's first-80%/last-20% cut with normalization statistics from
the FULL file. Place ``housing.data`` in ``DATA_HOME/uci_housing/``.
Deprecated in favor of ``paddle_tpu.text.datasets.UCIHousing``.
"""
from __future__ import annotations

import numpy as np

from . import common

__all__ = []

_cache = {}


def load_data(filename, feature_num=14, ratio=0.8):
    if "train" in _cache:
        return
    data = np.fromfile(filename, sep=" ")
    data = data.reshape(data.shape[0] // feature_num, feature_num)
    maximums, minimums = data.max(axis=0), data.min(axis=0)
    avgs = data.mean(axis=0)
    for i in range(feature_num - 1):
        data[:, i] = (data[:, i] - avgs[i]) / (maximums[i] - minimums[i])
    offset = int(data.shape[0] * ratio)
    _cache["train"], _cache["test"] = data[:offset], data[offset:]


def feature_range(maximums, minimums):
    # the reference plots the ranges with matplotlib (uci_housing.py:48);
    # here it just returns them
    return list(zip(minimums, maximums))


def _split(mode):
    load_data(common.local_path("uci_housing", "housing.data"))

    def reader():
        for d in _cache[mode]:
            yield d[:-1], d[-1:]

    return reader


def train():
    """Reader creator over the normalized 80% train cut."""
    return _split("train")


def test():
    """Reader creator over the normalized 20% test cut."""
    return _split("test")


def fetch():
    common.local_path("uci_housing", "housing.data")
