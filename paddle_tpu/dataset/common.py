"""Shared plumbing for the legacy ``paddle.dataset`` namespace.

Reference: ``python/paddle/dataset/common.py:41-230``. The one semantic
change: this build has zero network egress, so ``download`` verifies a
pre-placed file instead of fetching — every dataset documents the
conventional location under ``DATA_HOME`` where its standard archive
must be put (the same layout the reference's downloader produces).
"""
from __future__ import annotations

import glob
import hashlib
import os
import pickle

from ..core.errors import InvalidArgumentError

__all__ = []

DATA_HOME = os.path.expanduser(os.path.join("~", ".cache", "paddle",
                                            "dataset"))


def must_mkdirs(path):
    os.makedirs(path, exist_ok=True)


def md5file(fname):
    h = hashlib.md5()
    with open(fname, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def download(url, module_name, md5sum, save_name=None):
    """Resolve the conventional local path for a dataset file.

    The reference fetches ``url`` into ``DATA_HOME/module_name`` and
    md5-verifies it (``common.py:62``). Zero-egress build: the file must
    already be there (md5 is checked when given); otherwise this raises
    with the exact path to place it at.
    """
    dirname = os.path.join(DATA_HOME, module_name)
    filename = os.path.join(
        dirname, save_name if save_name else url.split("/")[-1])
    if os.path.exists(filename):
        if md5sum and md5file(filename) != md5sum:
            raise InvalidArgumentError(
                "%s exists but fails md5 verification (want %s)"
                % (filename, md5sum))
        return filename
    raise InvalidArgumentError(
        "no-egress build cannot download %s; place the file at %s"
        % (url, filename))


def local_path(module_name, filename, hint=""):
    """``DATA_HOME/module_name/filename`` if present, else a helpful error."""
    path = os.path.join(DATA_HOME, module_name, filename)
    if os.path.exists(path):
        return path
    raise InvalidArgumentError(
        "paddle.dataset.%s: expected %s%s (no-egress build; place the "
        "standard archive there)" % (module_name, path,
                                     " — " + hint if hint else ""))


def split(reader, line_count, suffix="%05d.pickle", dumper=pickle.dump):
    """Shard a reader's output into pickle files of ``line_count`` samples."""
    if not callable(reader):
        raise TypeError("reader should be callable")
    lines = []
    index = 0
    for item in reader():
        lines.append(item)
        if len(lines) >= line_count:
            with open(suffix % index, "wb") as f:
                dumper(lines, f)
            lines = []
            index += 1
    if lines:
        with open(suffix % index, "wb") as f:
            dumper(lines, f)
        index += 1
    return index


def cluster_files_reader(files_pattern, trainer_count, trainer_id,
                         loader=pickle.load):
    """Read this trainer's shard (round-robin by file) of pickled sample
    files produced by :func:`split`."""

    def reader():
        flist = sorted(glob.glob(files_pattern))
        for i, fname in enumerate(flist):
            if i % trainer_count == trainer_id:
                with open(fname, "rb") as f:
                    for item in loader(f):
                        yield item

    return reader
