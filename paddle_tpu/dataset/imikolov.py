"""Legacy PTB (imikolov) readers (``paddle.dataset.imikolov``).

Reference: ``python/paddle/dataset/imikolov.py:42-168``. N-gram windows
or (src, trg) id sequences over the Penn Treebank simple-examples
archive; vocabulary from train+valid with frequency ``> min_word_freq``,
``<unk>`` last. Place ``simple-examples.tgz`` in ``DATA_HOME/imikolov/``.
"""
from __future__ import annotations

import collections
import tarfile

from . import common

__all__ = []


class DataType:
    NGRAM = 1
    SEQ = 2


def _tar_path():
    return common.local_path("imikolov", "simple-examples.tgz")


def _extract(tf, filename):
    names = tf.getnames()
    if filename not in names and filename.startswith("./") \
            and filename[2:] in names:
        filename = filename[2:]
    return tf.extractfile(filename)


def word_count(f, word_freq=None):
    if word_freq is None:
        word_freq = collections.defaultdict(int)
    for line in f:
        for w in line.strip().split():
            word_freq[w] += 1
        word_freq[b"<s>"] += 1
        word_freq[b"<e>"] += 1
    return word_freq


def build_dict(min_word_freq=50):
    """Vocabulary over ptb.train + ptb.valid: ids ranked by (-freq, word)
    for frequency > ``min_word_freq``; ``<unk>`` last."""
    with tarfile.open(_tar_path()) as tf:
        trainf = _extract(tf, "./simple-examples/data/ptb.train.txt")
        validf = _extract(tf, "./simple-examples/data/ptb.valid.txt")
        word_freq = word_count(validf, word_count(trainf))
    word_freq.pop(b"<unk>", None)
    kept = sorted(((w, c) for w, c in word_freq.items()
                   if c > min_word_freq), key=lambda x: (-x[1], x[0]))
    word_idx = {w: i for i, (w, _) in enumerate(kept)}
    word_idx[b"<unk>"] = len(kept)
    return word_idx


def reader_creator(filename, word_idx, n, data_type):
    def reader():
        with tarfile.open(_tar_path()) as tf:
            f = _extract(tf, filename)
            unk = word_idx[b"<unk>"]
            for line in f:
                if data_type == DataType.NGRAM:
                    if n <= 0:
                        raise ValueError("Invalid gram length")
                    words = [b"<s>"] + line.strip().split() + [b"<e>"]
                    if len(words) >= n:
                        ids = [word_idx.get(w, unk) for w in words]
                        for i in range(n, len(ids) + 1):
                            yield tuple(ids[i - n:i])
                elif data_type == DataType.SEQ:
                    ids = [word_idx.get(w, unk)
                           for w in line.strip().split()]
                    src = [word_idx[b"<s>"]] + ids
                    trg = ids + [word_idx[b"<e>"]]
                    if n > 0 and len(src) > n:
                        continue
                    yield src, trg
                else:
                    raise ValueError("Unknown data type %r" % data_type)

    return reader


def train(word_idx, n, data_type=DataType.NGRAM):
    """Train reader creator (ptb.train.txt)."""
    return reader_creator("./simple-examples/data/ptb.train.txt", word_idx,
                          n, data_type)


def test(word_idx, n, data_type=DataType.NGRAM):
    """Test reader creator (ptb.valid.txt, as in the reference)."""
    return reader_creator("./simple-examples/data/ptb.valid.txt", word_idx,
                          n, data_type)


def fetch():
    _tar_path()
