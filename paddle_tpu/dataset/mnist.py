"""Legacy MNIST readers (``paddle.dataset.mnist``).

Reference: ``python/paddle/dataset/mnist.py:43-140``. Samples are
(flattened 784 float32 pixels in [-1, 1], int label). Deprecated in
favor of ``paddle_tpu.vision.datasets.MNIST`` (whose IDX parser this
delegates to); archives go in ``DATA_HOME/mnist/`` under their standard
names (``train-images-idx3-ubyte.gz`` etc.).
"""
from __future__ import annotations

import numpy as np

from . import common

__all__ = []

_FILES = {
    "train": ("train-images-idx3-ubyte.gz", "train-labels-idx1-ubyte.gz"),
    "test": ("t10k-images-idx3-ubyte.gz", "t10k-labels-idx1-ubyte.gz"),
}


def reader_creator(image_filename, label_filename, buffer_size=100):
    from ..vision.datasets import _read_idx_images, _read_idx_labels

    def reader():
        images = _read_idx_images(image_filename)
        labels = _read_idx_labels(label_filename)
        flat = images.reshape(len(images), -1).astype("float32")
        flat = flat / 255.0 * 2.0 - 1.0
        for img, label in zip(flat, labels):
            yield img, int(label)

    return reader


def _split(mode):
    img, lab = _FILES[mode]
    return reader_creator(common.local_path("mnist", img),
                          common.local_path("mnist", lab))


def train():
    """Reader creator over the training split ([-1, 1] pixels, int label)."""
    return _split("train")


def test():
    """Reader creator over the test split ([-1, 1] pixels, int label)."""
    return _split("test")


def fetch():
    for img, lab in _FILES.values():
        common.local_path("mnist", img)
        common.local_path("mnist", lab)
