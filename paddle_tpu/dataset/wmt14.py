"""Legacy WMT14 en→fr readers (``paddle.dataset.wmt14``).

Reference: ``python/paddle/dataset/wmt14.py:52-190``. Delegates to
``paddle_tpu.text.datasets.WMT14`` (same (src, trg, trg_next) samples
with <s>/<e>/<unk> framing). Place the preprocessed ``wmt14.tgz`` in
``DATA_HOME/wmt14/``.
"""
from __future__ import annotations

from . import common

__all__ = []


def _dataset(mode, dict_size):
    from ..text.datasets import WMT14

    return WMT14(data_file=common.local_path("wmt14", "wmt14.tgz"),
                 mode=mode, dict_size=dict_size)


def _reader_creator(mode, dict_size):
    def reader():
        ds = _dataset(mode, dict_size)
        for sample in ds:
            yield tuple(sample)

    return reader


def train(dict_size):
    """Train reader creator: (src_ids, trg_ids, trg_ids_next)."""
    return _reader_creator("train", dict_size)


def test(dict_size):
    """Test reader creator."""
    return _reader_creator("test", dict_size)


def gen(dict_size):
    """Generation-split reader creator (the archive's ``gen`` file)."""
    return _reader_creator("gen", dict_size)


def get_dict(dict_size, reverse=True):
    """(src_dict, trg_dict); ``reverse=True`` maps id→word."""
    ds = _dataset("train", dict_size)
    src, trg = ds.src_dict, ds.trg_dict
    if reverse:
        src = {v: k for k, v in src.items()}
        trg = {v: k for k, v in trg.items()}
    return src, trg


def fetch():
    common.local_path("wmt14", "wmt14.tgz")
