"""Legacy IMDB sentiment readers (``paddle.dataset.imdb``).

Reference: ``python/paddle/dataset/imdb.py:40-150``. Legacy semantics
kept exactly: punctuation-stripped lowercase tokenization, frequency
``> cutoff`` vocabulary ranked by (-freq, word) with ``<unk>`` last, and
labels pos=0 / neg=1 (note the modern ``text.datasets.Imdb`` uses the
opposite convention, neg=0/pos=1). Place ``aclImdb_v1.tar.gz`` in
``DATA_HOME/imdb/``.
"""
from __future__ import annotations

import collections
import re
import string
import tarfile

from . import common

__all__ = []

_PUNCT = bytes(string.punctuation, "ascii")


def _tar_path():
    return common.local_path("imdb", "aclImdb_v1.tar.gz")


def tokenize(pattern):
    """Yield the punctuation-stripped lowercase token list of every tar
    member matching ``pattern`` (sequential tar walk)."""
    with tarfile.open(_tar_path()) as tarf:
        member = tarf.next()
        while member is not None:
            if pattern.match(member.name):
                raw = tarf.extractfile(member).read().rstrip(b"\n\r")
                yield raw.translate(None, _PUNCT).lower().split()
            member = tarf.next()


def build_dict(pattern, cutoff):
    """Zero-based word ids for words with frequency > ``cutoff``, ranked
    by (-freq, word); ``<unk>`` gets the last id."""
    word_freq = collections.defaultdict(int)
    for doc in tokenize(pattern):
        for word in doc:
            word_freq[word] += 1
    kept = sorted(((w, c) for w, c in word_freq.items() if c > cutoff),
                  key=lambda x: (-x[1], x[0]))
    word_idx = {w: i for i, (w, _) in enumerate(kept)}
    word_idx[b"<unk>"] = len(kept)
    return word_idx


def reader_creator(pos_pattern, neg_pattern, word_idx):
    unk = word_idx[b"<unk>"]
    samples = []

    def load(pattern, label):
        for doc in tokenize(pattern):
            samples.append(([word_idx.get(w, unk) for w in doc], label))

    load(pos_pattern, 0)
    load(neg_pattern, 1)

    def reader():
        yield from samples

    return reader


def train(word_idx):
    """Train reader creator: (word-id list, label) with pos=0, neg=1."""
    return reader_creator(
        re.compile(r"aclImdb/train/pos/.*\.txt$"),
        re.compile(r"aclImdb/train/neg/.*\.txt$"), word_idx)


def test(word_idx):
    """Test reader creator: (word-id list, label) with pos=0, neg=1."""
    return reader_creator(
        re.compile(r"aclImdb/test/pos/.*\.txt$"),
        re.compile(r"aclImdb/test/neg/.*\.txt$"), word_idx)


def word_dict():
    """The corpus vocabulary (train+test, both polarities, cutoff 150)."""
    return build_dict(
        re.compile(r"aclImdb/((train)|(test))/((pos)|(neg))/.*\.txt$"), 150)


def fetch():
    _tar_path()
