"""Legacy CIFAR readers (``paddle.dataset.cifar``).

Reference: ``python/paddle/dataset/cifar.py:49-165``. Samples are
(flattened 3072 float32 pixels in [0, 1], int label). Deprecated in
favor of ``paddle_tpu.vision.datasets.Cifar10/Cifar100`` (whose tar
parser this delegates to); archives go in ``DATA_HOME/cifar/`` as
``cifar-10-python.tar.gz`` / ``cifar-100-python.tar.gz``.
"""
from __future__ import annotations

from . import common

__all__ = []


def _reader(kind, mode, cycle=False):
    from ..vision import datasets as vd

    cls = vd.Cifar10 if kind == 10 else vd.Cifar100
    path = common.local_path(
        "cifar", "cifar-%d-python.tar.gz" % kind)

    def reader():
        ds = cls(data_file=path, mode=mode)
        while True:
            # ds.data is the raw [N, 3, 32, 32] uint8 tensor; the legacy
            # sample is the CHW-ordered 3072-row (R then G then B planes),
            # NOT the HWC image __getitem__ serves to transforms
            for raw, label in zip(ds.data, ds.labels):
                yield raw.reshape(-1).astype("float32") / 255.0, int(label)
            if not cycle:
                break

    return reader


def train10(cycle=False):
    """CIFAR-10 train reader creator ([0, 1] pixels, label in [0, 9])."""
    return _reader(10, "train", cycle)


def test10(cycle=False):
    """CIFAR-10 test reader creator."""
    return _reader(10, "test", cycle)


def train100():
    """CIFAR-100 train reader creator (label in [0, 99])."""
    return _reader(100, "train")


def test100():
    """CIFAR-100 test reader creator."""
    return _reader(100, "test")


def fetch():
    common.local_path("cifar", "cifar-10-python.tar.gz")
    common.local_path("cifar", "cifar-100-python.tar.gz")
