"""Legacy WMT16 en↔de readers (``paddle.dataset.wmt16``).

Reference: ``python/paddle/dataset/wmt16.py:104-340``. Delegates to
``paddle_tpu.text.datasets.WMT16`` (train-split vocabularies with
<s>/<e>/<unk> first, then words by descending frequency, truncated to
the requested size; (src, trg, trg_next) samples). Place ``wmt16.tar.gz``
in ``DATA_HOME/wmt16/``.
"""
from __future__ import annotations

from . import common

__all__ = []


def _dataset(mode, src_dict_size, trg_dict_size, src_lang):
    from ..text.datasets import WMT16

    return WMT16(data_file=common.local_path("wmt16", "wmt16.tar.gz"),
                 mode=mode, src_dict_size=src_dict_size,
                 trg_dict_size=trg_dict_size, lang=src_lang)


def _reader_creator(mode, src_dict_size, trg_dict_size, src_lang):
    if src_lang not in ("en", "de"):
        raise ValueError("An error language type. Only support: en (for "
                         "English); de(for Germany).")

    def reader():
        ds = _dataset(mode, src_dict_size, trg_dict_size, src_lang)
        for sample in ds:
            yield tuple(sample)

    return reader


def train(src_dict_size, trg_dict_size, src_lang="en"):
    """Train reader creator: (src_ids, trg_ids, trg_ids_next)."""
    return _reader_creator("train", src_dict_size, trg_dict_size, src_lang)


def test(src_dict_size, trg_dict_size, src_lang="en"):
    """Test reader creator."""
    return _reader_creator("test", src_dict_size, trg_dict_size, src_lang)


def validation(src_dict_size, trg_dict_size, src_lang="en"):
    """Validation reader creator."""
    return _reader_creator("val", src_dict_size, trg_dict_size, src_lang)


def get_dict(lang, dict_size, reverse=False):
    """The vocabulary for ``lang`` ('en'|'de') at ``dict_size``;
    ``reverse=True`` maps id→word."""
    ds = _dataset("train",
                  dict_size if lang == "en" else -1,
                  dict_size if lang != "en" else -1, "en")
    d = ds.src_dict if lang == "en" else ds.trg_dict
    if reverse:
        d = {v: k for k, v in d.items()}
    return d


def fetch():
    common.local_path("wmt16", "wmt16.tar.gz")
