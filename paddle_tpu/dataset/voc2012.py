"""Legacy VOC2012 segmentation readers (``paddle.dataset.voc2012``).

Reference: ``python/paddle/dataset/voc2012.py:44-110`` — note its split
quirk is intentional: ``train()`` reads the 2913-image trainval list,
``test()`` the 1464-image train list, ``val()`` the val list. Delegates
to ``paddle_tpu.vision.datasets.VOC2012`` (which keeps the same mapping).
Place ``VOCtrainval_11-May-2012.tar`` in ``DATA_HOME/voc2012/``.
"""
from __future__ import annotations

import numpy as np

from . import common

__all__ = []


def _reader(mode):
    from ..vision.datasets import VOC2012

    path = common.local_path("voc2012", "VOCtrainval_11-May-2012.tar")

    def reader():
        ds = VOC2012(data_file=path, mode=mode)
        for img, label in ds:
            yield np.asarray(img), np.asarray(label)

    return reader


def train():
    """Reader over the 2913-image trainval list (HWC uint8, label mask)."""
    return _reader("train")


def test():
    """Reader over the 1464-image train list (the reference's mapping)."""
    return _reader("test")


def val():
    """Reader over the 1449-image val list."""
    return _reader("valid")


def fetch():
    common.local_path("voc2012", "VOCtrainval_11-May-2012.tar")
