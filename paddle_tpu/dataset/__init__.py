"""Legacy ``paddle.dataset`` namespace (deprecated in the reference since
2.0 in favor of ``paddle.vision.datasets`` / ``paddle.text.datasets``,
kept for API parity; reference ``python/paddle/dataset/__init__.py``).

Zero-egress build: nothing downloads. Each module documents the
conventional path under ``common.DATA_HOME`` where its standard archive
must be placed; most modules delegate parsing to the modern dataset
classes in ``paddle_tpu.vision``/``paddle_tpu.text``.
"""
from . import (  # noqa: F401
    cifar,
    common,
    conll05,
    flowers,
    image,
    imdb,
    imikolov,
    mnist,
    movielens,
    uci_housing,
    voc2012,
    wmt14,
    wmt16,
)

__all__ = [
    "mnist", "imikolov", "imdb", "cifar", "movielens", "conll05",
    "uci_housing", "wmt14", "wmt16", "flowers", "voc2012", "image",
    "common",
]
