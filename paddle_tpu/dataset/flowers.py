"""Legacy Flowers-102 readers (``paddle.dataset.flowers``).

Reference: ``python/paddle/dataset/flowers.py:85-240``. Delegates to
``paddle_tpu.vision.datasets.Flowers``; the legacy mapper/xmap options
are honored via ``paddle_tpu.reader`` decorators. Conventional files in
``DATA_HOME/flowers/``: ``102flowers.tgz``, ``imagelabels.mat``,
``setid.mat``.
"""
from __future__ import annotations

import numpy as np

from . import common
from .. import reader as reader_mod

__all__ = []


def default_mapper(is_train, sample):
    """The reference resizes short side to 256 then crops 224 (random for
    train, center for test) via its image module; same here."""
    from . import image

    img, label = sample
    img = image.simple_transform(np.asarray(img), 256, 224, is_train)
    return img.flatten().astype("float32"), label


train_mapper = lambda sample: default_mapper(True, sample)  # noqa: E731
test_mapper = lambda sample: default_mapper(False, sample)  # noqa: E731


def reader_creator(data_file, label_file, setid_file, dataset_name,
                   mapper, buffered_size=1024, use_xmap=True, cycle=False):
    from ..vision.datasets import Flowers

    mode = {"trnid": "train", "tstid": "test", "valid": "valid"}[dataset_name]

    def base():
        ds = Flowers(data_file=data_file, label_file=label_file,
                     setid_file=setid_file, mode=mode)
        while True:
            for img, label in ds:
                yield np.asarray(img), int(label)
            if not cycle:
                break

    if mapper is None:
        return base
    if use_xmap:
        return reader_mod.xmap_readers(mapper, base, 4, buffered_size)
    return reader_mod.map_readers(mapper, base)


def _files():
    return (common.local_path("flowers", "102flowers.tgz"),
            common.local_path("flowers", "imagelabels.mat"),
            common.local_path("flowers", "setid.mat"))


def train(mapper=train_mapper, buffered_size=1024, use_xmap=True,
          cycle=False):
    """Train reader creator (flattened transformed pixels, label)."""
    d, l, s = _files()
    return reader_creator(d, l, s, "trnid", mapper, buffered_size, use_xmap,
                          cycle)


def test(mapper=test_mapper, buffered_size=1024, use_xmap=True, cycle=False):
    """Test reader creator."""
    d, l, s = _files()
    return reader_creator(d, l, s, "tstid", mapper, buffered_size, use_xmap,
                          cycle)


def valid(mapper=test_mapper, buffered_size=1024, use_xmap=True):
    """Validation reader creator."""
    d, l, s = _files()
    return reader_creator(d, l, s, "valid", mapper, buffered_size, use_xmap)


def fetch():
    _files()
