"""Legacy image helpers (``paddle.dataset.image``).

Reference: ``python/paddle/dataset/image.py:76-410``. HWC uint8 numpy in,
numpy out; decoding prefers cv2 and falls back to PIL (the reference is
cv2-only). These are host-side preprocessing utilities — device-side
augmentation lives in ``paddle_tpu.vision.transforms``.
"""
from __future__ import annotations

import io
import pickle
import tarfile

import numpy as np

__all__ = []


def _decode(data, is_color):
    try:
        import cv2

        flag = cv2.IMREAD_COLOR if is_color else cv2.IMREAD_GRAYSCALE
        img = cv2.imdecode(np.frombuffer(data, np.uint8), flag)
        if img is None:
            raise ValueError("cv2 failed to decode image bytes")
        return img
    except ImportError:
        from PIL import Image

        img = Image.open(io.BytesIO(data))
        img = img.convert("RGB" if is_color else "L")
        arr = np.asarray(img)
        # match cv2's BGR channel order so downstream mean values line up
        return arr[:, :, ::-1] if is_color else arr


def load_image_bytes(bytes, is_color=True):  # noqa: A002
    """Decode an in-memory encoded image to HWC (color) / HW (gray)."""
    return _decode(bytes, is_color)


def load_image(file, is_color=True):  # noqa: A002
    """Load and decode an image file."""
    with open(file, "rb") as f:
        return _decode(f.read(), is_color)


def resize_short(im, size):
    """Resize so the shorter edge equals ``size``, keeping aspect ratio."""
    h, w = im.shape[:2]
    if h > w:
        new_h, new_w = size * h // w, size
    else:
        new_h, new_w = size, size * w // h
    try:
        import cv2

        return cv2.resize(im, (new_w, new_h),
                          interpolation=cv2.INTER_CUBIC)
    except ImportError:
        from PIL import Image

        mode = Image.fromarray(im)
        return np.asarray(mode.resize((new_w, new_h), Image.BICUBIC))


def to_chw(im, order=(2, 0, 1)):
    """HWC → CHW (or any axis permutation)."""
    assert len(im.shape) == len(order)
    return im.transpose(order)


def center_crop(im, size, is_color=True):
    """Crop the center ``size``×``size`` patch."""
    h, w = im.shape[:2]
    h_start = (h - size) // 2
    w_start = (w - size) // 2
    if is_color:
        return im[h_start:h_start + size, w_start:w_start + size, :]
    return im[h_start:h_start + size, w_start:w_start + size]


def random_crop(im, size, is_color=True):
    """Crop a random ``size``×``size`` patch."""
    h, w = im.shape[:2]
    h_start = np.random.randint(0, h - size + 1)
    w_start = np.random.randint(0, w - size + 1)
    if is_color:
        return im[h_start:h_start + size, w_start:w_start + size, :]
    return im[h_start:h_start + size, w_start:w_start + size]


def left_right_flip(im, is_color=True):
    """Mirror horizontally."""
    if len(im.shape) == 3 and is_color:
        return im[:, ::-1, :]
    return im[:, ::-1]


def simple_transform(im, resize_size, crop_size, is_train, is_color=True,
                     mean=None):
    """resize-short → (random crop + coin-flip mirror | center crop) →
    CHW float32 → optional mean subtraction (per-channel or elementwise)."""
    im = resize_short(im, resize_size)
    if is_train:
        im = random_crop(im, crop_size, is_color=is_color)
        if np.random.randint(2) == 0:
            im = left_right_flip(im, is_color)
    else:
        im = center_crop(im, crop_size, is_color=is_color)
    if len(im.shape) == 3:
        im = to_chw(im)
    im = im.astype("float32")
    if mean is not None:
        mean = np.array(mean, dtype=np.float32)
        if mean.ndim == 1 and is_color:
            mean = mean[:, np.newaxis, np.newaxis]
        im -= mean
    return im


def load_and_transform(filename, resize_size, crop_size, is_train,
                       is_color=True, mean=None):
    """:func:`load_image` then :func:`simple_transform`."""
    return simple_transform(load_image(filename, is_color), resize_size,
                            crop_size, is_train, is_color, mean)


def batch_images_from_tar(data_file, dataset_name, img2label,
                          num_per_batch=1024):
    """Pickle (image-bytes, label) batches out of a tar of images.

    Writes ``<data_file>_batch/<dataset_name>_%05d`` files plus a
    ``meta`` file listing them; returns the meta path (the reference's
    preprocessing helper for cluster training, ``image.py:76``)."""
    import os

    out_path = "%s_batch" % data_file
    meta_file = os.path.join(out_path, "%s_batch.meta" % dataset_name)
    # the meta file is written LAST, so its presence means a complete
    # build; a bare directory from a crashed run is rebuilt, not trusted
    if os.path.exists(meta_file):
        return meta_file
    os.makedirs(out_path, exist_ok=True)

    labels, data, file_id = [], [], 0
    with tarfile.open(data_file) as tf:
        for member in tf.getmembers():
            if member.name in img2label:
                data.append(tf.extractfile(member).read())
                labels.append(img2label[member.name])
                if len(data) == num_per_batch:
                    output = {"label": labels, "data": data}
                    with open(os.path.join(
                            out_path, "%s_%05d" % (dataset_name, file_id)),
                            "wb") as f:
                        pickle.dump(output, f, protocol=2)
                    file_id += 1
                    data, labels = [], []
    if data:
        output = {"label": labels, "data": data}
        with open(os.path.join(out_path, "%s_%05d"
                               % (dataset_name, file_id)), "wb") as f:
            pickle.dump(output, f, protocol=2)

    with open(meta_file, "w") as meta:  # "w": a rebuild must not append
        for file in sorted(os.listdir(out_path)):
            if not file.endswith(".meta"):
                meta.write(os.path.abspath(
                    os.path.join(out_path, file)) + "\n")
    return meta_file
