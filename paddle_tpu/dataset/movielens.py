"""Legacy MovieLens ml-1m readers (``paddle.dataset.movielens``).

Reference: ``python/paddle/dataset/movielens.py:45-300``. Samples are
``usr.value() + mov.value() + [[rating]]`` with rating rescaled to
[-5, 5] by ``r*2-5`` and a per-line random train/test split. Place
``ml-1m.zip`` in ``DATA_HOME/movielens/``. Delta vs the reference:
title-word and category ids are assigned in sorted order (its set
iteration order is interpreter-dependent). The train/test split stream
is NOT a delta: the reference seeds the global numpy RNG
(``np.random.seed(rand_seed)`` then ``np.random.random()``,
``python/paddle/dataset/movielens.py:152,157``) and a fresh
``np.random.RandomState(rand_seed).random_sample()`` yields the
bit-identical MT19937 sequence — same per-line membership — without
mutating global RNG state.
"""
from __future__ import annotations

import functools
import re
import zipfile

import numpy as np

from . import common

__all__ = []

age_table = [1, 18, 25, 35, 45, 50, 56]


class MovieInfo:
    """Movie id, title and categories."""

    def __init__(self, index, categories, title):
        self.index = int(index)
        self.categories = categories
        self.title = title

    def value(self):
        return [self.index,
                [CATEGORIES_DICT[c] for c in self.categories],
                [MOVIE_TITLE_DICT[w.lower()] for w in self.title.split()]]

    def __repr__(self):
        return "<MovieInfo id(%d), title(%s), categories(%s)>" % (
            self.index, self.title, self.categories)


class UserInfo:
    """User id, gender, age bucket and job."""

    def __init__(self, index, gender, age, job_id):
        self.index = int(index)
        self.is_male = gender == "M"
        self.age = age_table.index(int(age))
        self.job_id = int(job_id)

    def value(self):
        return [self.index, 0 if self.is_male else 1, self.age, self.job_id]

    def __repr__(self):
        return "<UserInfo id(%d), gender(%s), age(%d), job(%d)>" % (
            self.index, "M" if self.is_male else "F",
            age_table[self.age], self.job_id)


MOVIE_INFO = None
MOVIE_TITLE_DICT = None
CATEGORIES_DICT = None
USER_INFO = None


def _init():
    global MOVIE_INFO, MOVIE_TITLE_DICT, CATEGORIES_DICT, USER_INFO
    fn = common.local_path("movielens", "ml-1m.zip")
    if MOVIE_INFO is not None:
        return fn
    pattern = re.compile(r"^(.*)\((\d+)\)$")
    MOVIE_INFO = {}
    title_words, categories = set(), set()
    with zipfile.ZipFile(fn) as package:
        with package.open("ml-1m/movies.dat") as f:
            for line in f:
                mid, title, cats = line.decode("latin1").strip().split("::")
                cats = cats.split("|")
                categories.update(cats)
                title = pattern.match(title).group(1)
                MOVIE_INFO[int(mid)] = MovieInfo(mid, cats, title)
                title_words.update(w.lower() for w in title.split())
        MOVIE_TITLE_DICT = {w: i for i, w in enumerate(sorted(title_words))}
        CATEGORIES_DICT = {c: i for i, c in enumerate(sorted(categories))}
        USER_INFO = {}
        with package.open("ml-1m/users.dat") as f:
            for line in f:
                uid, gender, age, job, _zip = \
                    line.decode("latin1").strip().split("::")
                USER_INFO[int(uid)] = UserInfo(uid, gender, age, job)
    return fn


def _reader(rand_seed=0, test_ratio=0.1, is_test=False):
    fn = _init()
    # same MT19937 stream as the reference's np.random.seed(rand_seed) +
    # np.random.random() split, without touching global RNG state
    rng = np.random.RandomState(rand_seed)
    with zipfile.ZipFile(fn) as package:
        with package.open("ml-1m/ratings.dat") as f:
            for line in f:
                if (rng.random_sample() < test_ratio) == is_test:
                    uid, mid, rating, _ts = \
                        line.decode("latin1").strip().split("::")
                    usr = USER_INFO[int(uid)]
                    mov = MOVIE_INFO[int(mid)]
                    rating = float(rating) * 2 - 5.0
                    yield usr.value() + mov.value() + [[rating]]


def _reader_creator(**kwargs):
    return lambda: _reader(**kwargs)


train = functools.partial(_reader_creator, is_test=False)
test = functools.partial(_reader_creator, is_test=True)


def get_movie_title_dict():
    _init()
    return MOVIE_TITLE_DICT


def movie_categories():
    _init()
    return CATEGORIES_DICT


def max_movie_id():
    _init()
    return max(m.index for m in MOVIE_INFO.values())


def max_user_id():
    _init()
    return max(u.index for u in USER_INFO.values())


def max_job_id():
    _init()
    return max(u.job_id for u in USER_INFO.values())


def user_info():
    _init()
    return USER_INFO


def movie_info():
    _init()
    return MOVIE_INFO


def fetch():
    _init()
