"""Legacy CoNLL-05 SRL readers (``paddle.dataset.conll05``).

Reference: ``python/paddle/dataset/conll05.py:49-265``. Delegates to
``paddle_tpu.text.datasets.Conll05st`` (same 9-tuple sample schema).
Conventional files under ``DATA_HOME/conll05st/``:
``conll05st-tests.tar.gz``, ``wordDict.txt``, ``verbDict.txt``,
``targetDict.txt``, and (for :func:`get_embedding`) ``emb``.
"""
from __future__ import annotations

import numpy as np

from . import common

__all__ = []


def _dataset():
    from ..text.datasets import Conll05st

    return Conll05st(
        data_file=common.local_path("conll05st", "conll05st-tests.tar.gz"),
        word_dict_file=common.local_path("conll05st", "wordDict.txt"),
        verb_dict_file=common.local_path("conll05st", "verbDict.txt"),
        target_dict_file=common.local_path("conll05st", "targetDict.txt"))


def get_dict():
    """(word_dict, verb_dict, label_dict) of the corpus."""
    ds = _dataset()
    return ds.word_dict, ds.predicate_dict, ds.label_dict


def get_embedding():
    """The pre-trained word embedding table (float32 [vocab, dim]),
    whitespace-separated rows in the conventional ``emb`` file."""
    path = common.local_path("conll05st", "emb")
    return np.loadtxt(path, dtype=np.float32)


def test():
    """Test-section reader creator yielding the reference's 9-tuple
    (word, ctx_n2, ctx_n1, ctx_0, ctx_p1, ctx_p2, pred, mark, label)."""
    ds = _dataset()

    def reader():
        for sample in ds:
            yield tuple(sample)

    return reader


def fetch():
    _dataset()
