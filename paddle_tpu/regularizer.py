"""Weight-decay regularizers (reference: python/paddle/regularizer.py /
fluid/regularizer.py).  Applied by optimizers as grad += coeff * f(param).
"""
from __future__ import annotations

import jax.numpy as jnp


class WeightDecayRegularizer:
    def __init__(self, coeff: float = 0.0):
        self._coeff = float(coeff)

    @property
    def coeff(self) -> float:
        return self._coeff

    def __call__(self, param, grad):
        raise NotImplementedError


class L2Decay(WeightDecayRegularizer):
    def __call__(self, param, grad):
        return grad + self._coeff * param


class L1Decay(WeightDecayRegularizer):
    def __call__(self, param, grad):
        return grad + self._coeff * jnp.sign(param)
