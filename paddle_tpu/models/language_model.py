"""Decoder-only / encoder-only transformer language models — the flagship
benchmark workloads (BASELINE.md configs #3 BERT-base and #5 GPT-1.3B).

Built entirely from ``paddle_tpu.nn`` blocks (MultiHeadAttention /
TransformerEncoder — reference ``nn/layer/transformer.py:109,622``) with a
tied-embedding LM head and fused softmax-cross-entropy loss
(``operators/softmax_with_cross_entropy_op.cc:325`` semantics).

TPU-native notes: everything is static-shape and MXU-friendly (bf16-ready
matmuls, no data-dependent control flow); the causal mask is additive and
broadcast, so XLA fuses it into the attention softmax.
"""
from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from .. import tensor as T
from ..core.errors import InvalidArgumentError
from ..framework.tensor import Tensor
from ..nn import functional as F
from ..nn.layer.common import Dropout, Embedding, Linear
from ..nn.layer.layers import Layer
from ..nn.layer.norm import LayerNorm
from ..nn.layer.transformer import TransformerEncoder, TransformerEncoderLayer


def bert_base_config() -> dict:
    """BERT-base pretrain config (BASELINE.md workload #3)."""
    return dict(
        vocab_size=30528,  # 30522 padded to a multiple of 64 for the MXU
        hidden_size=768,
        num_layers=12,
        num_heads=12,
        intermediate_size=3072,
        max_position=512,
        causal=False,
    )


def ernie_base_config() -> dict:
    """ERNIE-3.0-base-style encoder config (BASELINE.md workload #4:
    fine-tune under sharding stage 2/3).  Same transformer geometry as
    BERT-base with segment (token-type) embeddings enabled."""
    return dict(
        vocab_size=40000,  # ERNIE zh vocab (39979) padded to 64
        hidden_size=768,
        num_layers=12,
        num_heads=12,
        intermediate_size=3072,
        max_position=2048,
        causal=False,
        type_vocab_size=4,
    )


def gpt_1p3b_config() -> dict:
    """GPT-3 1.3B config (BASELINE.md workload #5)."""
    return dict(
        vocab_size=50304,  # 50257 padded to a multiple of 64
        hidden_size=2048,
        num_layers=24,
        num_heads=16,
        intermediate_size=8192,
        max_position=2048,
        causal=True,
    )


class TransformerLM(Layer):
    """Transformer language model with tied input/output embeddings."""

    #: decode-cache layouts gen_decode_cache can build (the positional
    #: K/V pair — jit.cache; nn.ssm.SSMLM conversely serves only
    #: "recurrent").  DecodeSession checks this at construction.
    cache_layouts = ("dense", "paged")

    def __init__(
        self,
        vocab_size: int = 30528,
        hidden_size: int = 768,
        num_layers: int = 12,
        num_heads: int = 12,
        intermediate_size: Optional[int] = None,
        max_position: int = 512,
        dropout: float = 0.1,
        activation: str = "gelu",
        causal: bool = True,
        normalize_before: bool = True,
        type_vocab_size: int = 0,
    ):
        super().__init__()
        intermediate_size = intermediate_size or 4 * hidden_size
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.num_heads = num_heads
        self.intermediate_size = intermediate_size
        self.causal = causal
        self.word_embeddings = Embedding(vocab_size, hidden_size)
        self.position_embeddings = Embedding(max_position, hidden_size)
        # segment embeddings (BERT/ERNIE token types); 0 disables
        self.token_type_embeddings = (
            Embedding(type_vocab_size, hidden_size)
            if type_vocab_size else None)
        self.embed_dropout = Dropout(dropout)
        layer = TransformerEncoderLayer(
            hidden_size,
            num_heads,
            intermediate_size,
            dropout=dropout,
            activation=activation,
            normalize_before=normalize_before,
        )
        self.encoder = TransformerEncoder(layer, num_layers)
        self.final_norm = LayerNorm(hidden_size)
        self._sequence_parallel = False

    def enable_sequence_parallel(self, group=None, mode: str = "ring"):
        """Train with the sequence dim sharded over the ``sep`` mesh axis.

        Every attention block switches to ring/Ulysses attention
        (``meta_parallel/sequence_parallel.py``); causality moves from the
        materialized additive mask into the SP kernel, so no [L, L] mask is
        ever built.  Activations between blocks are per-position math that
        GSPMD shards along the sequence automatically.
        """
        for enc_layer in self.encoder.layers:
            enc_layer.self_attn.enable_sequence_parallel(
                group, mode=mode, causal=self.causal)
        self._sequence_parallel = True
        return self

    def _causal_mask(self, seq_len: int, dtype):
        # additive mask: 0 on/below diagonal, -inf above
        idx = jnp.arange(seq_len)
        allow = idx[None, :] <= idx[:, None]
        return jnp.where(allow, 0.0, jnp.finfo(jnp.float32).min).astype(dtype)

    def gen_decode_cache(self, batch_size: int, max_length: int,
                         dtype="float32", per_slot: bool = False,
                         layout: str = "dense", block_size: int = 32,
                         num_blocks: Optional[int] = None):
        """Per-layer preallocated KV decode cache (see
        ``MultiHeadAttention.gen_decode_cache``); thread it through
        ``forward(..., cache=...)`` for O(1)-per-token generation.
        ``layout="paged"`` selects the block-table cache
        (``PagedDecodeCache``) whose HBM scales with allocated tokens;
        ``dtype="int8"`` stores K/V quantized with per-head fp32 scales
        (quantize-on-write, dequant inside the attention — docs/DESIGN.md
        §5d), cutting the bytes every decode step streams ~4x vs fp32.
        Unsupported dtypes raise a typed error naming the supported set.

        Causal models only: the cached path masks attention causally over
        the prefix, which for a bidirectional (``causal=False``) encoder
        would silently CHANGE the math rather than just the cost — and
        incremental decoding is ill-defined there anyway (every new token
        would retroactively change all earlier hidden states)."""
        if not self.causal:
            raise InvalidArgumentError(
                "decode caching requires a causal model: a "
                "causal=False (bidirectional) encoder cannot decode "
                "incrementally — new tokens would change every earlier "
                "position's hidden state")
        return self.encoder.gen_decode_cache(batch_size, max_length, dtype,
                                             per_slot, layout, block_size,
                                             num_blocks)

    def encode(self, input_ids, attn_mask=None, token_type_ids=None,
               cache=None):
        """Final hidden states [B, L, H] (the backbone for task heads).

        With ``cache`` (a ``gen_decode_cache`` pytree) the input is an
        incremental chunk: positions start at the cache index, causality
        over the cached prefix is enforced INSIDE the attention (no
        [L, L] mask is built), and ``(hidden, new_cache)`` is returned.
        """
        seq_len = input_ids.shape[1]
        if cache is not None:
            idx = jnp.asarray(cache[0].index, jnp.int32)
            step = jnp.arange(seq_len, dtype=jnp.int32)
            # scalar index -> [L]; per-slot [B] index -> [B, L]
            pos = Tensor(idx + step if idx.ndim == 0
                         else idx[:, None] + step[None, :],
                         stop_gradient=True)
        else:
            pos = T.arange(0, seq_len, dtype="int64")
        h = self.word_embeddings(input_ids) + self.position_embeddings(pos)
        if self.token_type_embeddings is not None and token_type_ids is not None:
            h = h + self.token_type_embeddings(token_type_ids)
        h = self.embed_dropout(h)
        if cache is not None:
            h, new_cache = self.encoder(h, attn_mask, cache)
            return self.final_norm(h), new_cache
        if attn_mask is None and self.causal and not self._sequence_parallel:
            attn_mask = Tensor(
                self._causal_mask(seq_len, h.value.dtype), stop_gradient=True
            )
        h = self.encoder(h, attn_mask)
        return self.final_norm(h)

    def forward(self, input_ids, attn_mask=None, token_type_ids=None,
                cache=None):
        if cache is not None:
            h, new_cache = self.encode(input_ids, attn_mask, token_type_ids,
                                       cache)
            logits = T.matmul(h, self.word_embeddings.weight,
                              transpose_y=True)
            return logits, new_cache
        h = self.encode(input_ids, attn_mask, token_type_ids)
        # tied LM head: logits = h @ E^T
        logits = T.matmul(h, self.word_embeddings.weight, transpose_y=True)
        return logits

    def flops_per_token(self, seq_len: int) -> float:
        """Analytic fwd+bwd FLOPs/token for MFU accounting (PaLM appendix B).

        6 * n_params_matmul + attention term 12 * L * H * seq.
        """
        h, l, ff, v = self.hidden_size, self.num_layers, self.intermediate_size, self.vocab_size
        per_layer = 4 * h * h + 2 * h * ff  # qkvo + mlp matmul params
        matmul_params = l * per_layer + v * h  # + lm head (tied)
        attn = 12 * l * h * seq_len  # fwd+bwd qk^T and av matmuls
        return 6.0 * matmul_params + attn


class TransformerLMCriterion(Layer):
    """Next-token (or masked) LM loss with fused softmax cross-entropy."""

    def __init__(self, shift_labels: bool = True):
        super().__init__()
        self.shift_labels = shift_labels

    def forward(self, logits, labels):
        if self.shift_labels:
            logits = logits[:, :-1, :]
            labels = labels[:, 1:]
        v = logits.shape[-1]
        return F.cross_entropy(
            T.reshape(logits, [-1, v]), T.reshape(labels, [-1]), reduction="mean"
        )


class TransformerForSequenceClassification(Layer):
    """Encoder + BERT-style pooler + classifier (the ERNIE fine-tune head,
    BASELINE.md workload #4)."""

    def __init__(self, num_classes: int = 2, dropout: float = 0.1, **config):
        super().__init__()
        config.setdefault("causal", False)
        self.backbone = TransformerLM(dropout=dropout, **config)
        h = self.backbone.hidden_size
        self.pooler = Linear(h, h)
        self.classifier_dropout = Dropout(dropout)
        self.classifier = Linear(h, num_classes)

    def forward(self, input_ids, attn_mask=None, token_type_ids=None):
        hidden = self.backbone.encode(input_ids, attn_mask, token_type_ids)
        pooled = T.tanh(self.pooler(hidden[:, 0]))  # [CLS] pooling
        return self.classifier(self.classifier_dropout(pooled))
