"""``paddle_tpu.models`` — reference model zoo built purely on ``paddle_tpu.nn``.

Reference parity: the BASELINE.md workload ladder (LeNet → ResNet50 →
BERT-base → ERNIE → GPT-1.3B); the transformer stack mirrors what
``python/paddle/nn/layer/transformer.py`` (MultiHeadAttention:109,
TransformerEncoder:622) is used for in the reference's NLP model zoo.
Vision CNNs live in ``paddle_tpu.vision.models``.
"""
from .language_model import (  # noqa: F401
    TransformerForSequenceClassification,
    TransformerLM,
    TransformerLMCriterion,
    bert_base_config,
    ernie_base_config,
    gpt_1p3b_config,
)
