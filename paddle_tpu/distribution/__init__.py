"""``paddle_tpu.distribution`` — probability distributions.

Reference parity: ``python/paddle/distribution.py`` — ``Distribution:41``
(sample/entropy/log_prob/probs/kl_divergence surface), ``Uniform:168``,
``Normal:390``, ``Categorical:640``.

TPU-native: sampling draws from the framework PRNG stream
(``core.random.next_key``) so ``paddle_tpu.seed`` reproduces; math is jnp
compositions on the Tensor facade.
"""
from __future__ import annotations

import math
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..core.errors import InvalidArgumentError
from ..core.random import next_key
from ..framework.tensor import Tensor

__all__ = ["Distribution", "Uniform", "Normal", "Categorical",
           "MultivariateNormalDiag", "sampling_id"]


def _raw(x):
    if isinstance(x, Tensor):
        return x.value
    return jnp.asarray(x, jnp.float32) if not isinstance(x, jax.Array) else x


class Distribution:
    """distribution.py:41 parity."""

    def sample(self, shape=()):
        raise NotImplementedError

    def entropy(self):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def probs(self, value):
        raise NotImplementedError

    def kl_divergence(self, other):
        raise NotImplementedError


class Uniform(Distribution):
    """distribution.py:168 parity: U[low, high)."""

    def __init__(self, low, high, name=None):
        self.low = _raw(low)
        self.high = _raw(high)

    def sample(self, shape=(), seed=0):
        shape = tuple(shape)
        base = jnp.broadcast_shapes(jnp.shape(self.low), jnp.shape(self.high))
        u = jax.random.uniform(next_key(), shape + base, jnp.float32)
        return Tensor(self.low + u * (self.high - self.low), stop_gradient=True)

    def log_prob(self, value):
        v = _raw(value)
        inside = jnp.logical_and(v >= self.low, v < self.high)
        lp = jnp.where(inside, -jnp.log(self.high - self.low), -jnp.inf)
        return Tensor(lp, stop_gradient=True)

    def probs(self, value):
        return Tensor(jnp.exp(_raw(self.log_prob(value))), stop_gradient=True)

    def entropy(self):
        return Tensor(jnp.log(self.high - self.low), stop_gradient=True)


class Normal(Distribution):
    """distribution.py:390 parity: N(loc, scale)."""

    def __init__(self, loc, scale, name=None):
        self.loc = _raw(loc)
        self.scale = _raw(scale)

    def sample(self, shape=(), seed=0):
        shape = tuple(shape)
        base = jnp.broadcast_shapes(jnp.shape(self.loc), jnp.shape(self.scale))
        z = jax.random.normal(next_key(), shape + base, jnp.float32)
        return Tensor(self.loc + z * self.scale, stop_gradient=True)

    def entropy(self):
        base = jnp.broadcast_shapes(jnp.shape(self.loc), jnp.shape(self.scale))
        scale = jnp.broadcast_to(self.scale, base)
        return Tensor(0.5 + 0.5 * math.log(2 * math.pi) + jnp.log(scale),
                      stop_gradient=True)

    def log_prob(self, value):
        v = _raw(value)
        var = self.scale * self.scale
        return Tensor(
            -((v - self.loc) ** 2) / (2 * var)
            - jnp.log(self.scale) - 0.5 * math.log(2 * math.pi),
            stop_gradient=True)

    def probs(self, value):
        return Tensor(jnp.exp(_raw(self.log_prob(value))), stop_gradient=True)

    def kl_divergence(self, other: "Normal"):
        """distribution.py:604 parity: KL(self || other)."""
        if not isinstance(other, Normal):
            raise InvalidArgumentError("kl_divergence expects a Normal")
        var_ratio = (self.scale / other.scale) ** 2
        t1 = ((self.loc - other.loc) / other.scale) ** 2
        return Tensor(0.5 * (var_ratio + t1 - 1.0 - jnp.log(var_ratio)),
                      stop_gradient=True)


class Categorical(Distribution):
    """distribution.py:640 parity: unnormalized logits vector."""

    def __init__(self, logits, name=None):
        self.logits = _raw(logits)
        if self.logits.ndim < 1:
            raise InvalidArgumentError("Categorical logits must be >= 1-D")

    def _probs_arr(self):
        p = self.logits - jax.nn.logsumexp(self.logits, axis=-1, keepdims=True)
        return jnp.exp(p)

    def sample(self, shape=(), seed=0):
        shape = tuple(shape)
        idx = jax.random.categorical(
            next_key(), self.logits, axis=-1,
            shape=shape + self.logits.shape[:-1])
        return Tensor(idx, stop_gradient=True)

    def entropy(self):
        logp = self.logits - jax.nn.logsumexp(self.logits, axis=-1, keepdims=True)
        return Tensor(-(jnp.exp(logp) * logp).sum(-1), stop_gradient=True)

    def probs(self, value):
        v = _raw(value).astype(jnp.int32)
        p = self._probs_arr()
        if p.ndim == 1:  # one distribution, arbitrary-shaped value
            return Tensor(jnp.take(p, v, axis=-1), stop_gradient=True)
        return Tensor(jnp.take_along_axis(
            p, v[..., None], axis=-1).squeeze(-1), stop_gradient=True)

    def log_prob(self, value):
        return Tensor(jnp.log(_raw(self.probs(value))), stop_gradient=True)

    def kl_divergence(self, other: "Categorical"):
        if not isinstance(other, Categorical):
            raise InvalidArgumentError("kl_divergence expects a Categorical")
        logp = self.logits - jax.nn.logsumexp(self.logits, axis=-1, keepdims=True)
        logq = other.logits - jax.nn.logsumexp(other.logits, axis=-1, keepdims=True)
        return Tensor((jnp.exp(logp) * (logp - logq)).sum(-1),
                      stop_gradient=True)


class MultivariateNormalDiag(Distribution):
    """distribution.py MultivariateNormalDiag parity: N(loc, diag(scale))."""

    def __init__(self, loc, scale, name=None):
        self.loc = _raw(loc)
        self.scale = _raw(scale)  # [..., D, D] diagonal matrix per reference
        if self.scale.ndim < 2:
            raise InvalidArgumentError(
                "MultivariateNormalDiag scale must be a (batched) square "
                "matrix carrying the diagonal, got shape %s"
                % (self.scale.shape,))

    def _diag(self):
        return jnp.diagonal(self.scale, axis1=-2, axis2=-1)

    def sample(self, shape=(), seed=0):
        d = self._diag()
        base = jnp.broadcast_shapes(jnp.shape(self.loc), d.shape)
        z = jax.random.normal(next_key(), tuple(shape) + base, jnp.float32)
        return Tensor(self.loc + z * d, stop_gradient=True)

    def entropy(self):
        d = self._diag()
        D = d.shape[-1]
        return Tensor(0.5 * D * (1.0 + math.log(2 * math.pi))
                      + 0.5 * jnp.log(jnp.prod(jnp.square(d), axis=-1)),
                      stop_gradient=True)

    def log_prob(self, value):
        v = _raw(value)
        d = self._diag()
        quad = jnp.sum(jnp.square((v - self.loc) / d), axis=-1)
        D = d.shape[-1]
        return Tensor(-0.5 * (quad + D * math.log(2 * math.pi))
                      - jnp.sum(jnp.log(d), axis=-1), stop_gradient=True)

    def probs(self, value):
        return Tensor(jnp.exp(_raw(self.log_prob(value))),
                      stop_gradient=True)

    def kl_divergence(self, other: "MultivariateNormalDiag"):
        d1, d2 = self._diag(), other._diag()
        var1, var2 = jnp.square(d1), jnp.square(d2)
        D = d1.shape[-1]
        kl = 0.5 * (jnp.sum(var1 / var2, -1)
                    + jnp.sum(jnp.square(self.loc - other.loc) / var2, -1)
                    - D + jnp.log(jnp.prod(var2, -1) / jnp.prod(var1, -1)))
        return Tensor(kl, stop_gradient=True)


def sampling_id(x, min=0.0, max=1.0, seed=0, dtype="int64"):
    """fluid/layers sampling_id parity: sample one category id per row from
    a [batch, V] probability matrix."""
    p = _raw(x)
    if p.ndim != 2:
        raise InvalidArgumentError(
            "sampling_id expects [batch, V] probabilities, got %s"
            % (p.shape,))
    key = next_key()
    ids = jax.random.categorical(key, jnp.log(jnp.maximum(p, 1e-30)), axis=-1)
    # int64 requests land on int32 unless x64 is enabled (TPU-first default)
    want = jnp.dtype(dtype)
    if want == jnp.dtype("int64") and not jax.config.jax_enable_x64:
        want = jnp.dtype("int32")
    return Tensor(ids.astype(want), stop_gradient=True)
