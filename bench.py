"""Benchmark harness: BERT-base fused train step on one chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Workload: BASELINE.md config #3 (BERT-base pretrain shape, seq 512) through
the fully-jitted TrainStep (forward + backward + AdamW, donated buffers).
The reference publishes no absolute numbers (BASELINE.md: "published: {}"),
so ``vs_baseline`` reports measured model FLOPs utilization (MFU) against the
0.40 A100-class MFU target named in BASELINE.md's north star.
"""
from __future__ import annotations

import json
import time

import numpy as np


def main():
    import os

    import jax

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        # pre-registered accelerator plugins ignore the env var; force it
        jax.config.update("jax_platforms", "cpu")

    import paddle_tpu as pt
    from paddle_tpu.jit import TrainStep
    from paddle_tpu.models import TransformerLM, TransformerLMCriterion, bert_base_config

    pt.seed(0)
    on_tpu = jax.default_backend() not in ("cpu",)
    cfg = bert_base_config()
    if not on_tpu:  # CPU smoke: shrink so the harness itself stays testable
        cfg.update(num_layers=2, hidden_size=128, num_heads=2, intermediate_size=512,
                   vocab_size=1024)
    batch, seq = (16, 512) if on_tpu else (2, 128)

    model = TransformerLM(**cfg, dropout=0.0)
    criterion = TransformerLMCriterion(shift_labels=False)
    opt = pt.optimizer.AdamW(1e-4, parameters=model.parameters())
    # bf16 mixed precision: params/activations in bf16 (MXU native), fp32
    # master weights in the optimizer, loss math fp32 via the amp black list
    model, opt = pt.amp.decorate(model, opt, level="O2", dtype="bfloat16")

    def loss_fn(m, ids, labels):
        with pt.amp.auto_cast(level="O1", dtype="bfloat16"):
            return criterion(m(ids), labels)

    step = TrainStep(model, loss_fn, opt)
    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg["vocab_size"], (batch, seq)).astype("int32")

    # warmup (includes compile)
    for _ in range(2):
        loss = step(ids, ids)
    float(loss)

    iters = 10 if on_tpu else 3
    t0 = time.perf_counter()
    for _ in range(iters):
        loss = step(ids, ids)
    float(loss)  # block on the last step
    dt = (time.perf_counter() - t0) / iters

    tokens_per_sec = batch * seq / dt
    flops_per_step = model.flops_per_token(seq) * batch * seq
    # per-chip bf16 peak FLOP/s by device generation (standard MFU convention)
    kind = jax.devices()[0].device_kind.lower() if on_tpu else "cpu"
    if "v5 lite" in kind or "v5e" in kind:
        peak = 197e12
    elif "v5p" in kind or "v5" in kind:
        peak = 459e12
    elif "v4" in kind:
        peak = 275e12
    elif "v6" in kind or "trillium" in kind:
        peak = 918e12
    else:
        peak = 197e12 if on_tpu else 1e12
    mfu = flops_per_step / dt / peak
    print(json.dumps({
        "metric": "bert_base_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/s",
        "vs_baseline": round(mfu / 0.40, 4),
        "extra": {
            "step_time_s": round(dt, 4),
            "mfu": round(mfu, 4),
            "batch": batch,
            "seq": seq,
            "backend": jax.default_backend(),
            "loss": float(loss),
        },
    }))


if __name__ == "__main__":
    main()
