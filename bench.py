"""Benchmark harness: both BASELINE.md headline metrics on one chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "extra"}.

Workloads:
- **BERT-base pretrain** (BASELINE.md config #3, seq 512) through the
  fully-jitted TrainStep (forward + backward + AdamW, donated buffers) —
  the primary metric (tokens/s/chip).
- **ResNet50 ImageNet** (BASELINE.md config #2: compiled path + AMP) —
  reported in ``extra`` as imgs/sec/chip with its own MFU.

The reference publishes no absolute numbers (BASELINE.md: "published: {}"),
so ``vs_baseline`` reports measured model FLOPs utilization (MFU) against
the 0.40 A100-class MFU target named in BASELINE.md's north star.
"""
from __future__ import annotations

import json
import os
import time

import numpy as np

_REPO = os.path.dirname(os.path.abspath(__file__))
# Last verified on-chip record; bench.py WRITES this after every successful
# TPU run and PROMOTES it to the primary metric when the tunnel is down, so
# a dead tunnel at round end can never zero out the round's evidence.
_TPU_RECORD = os.path.join(_REPO, "TPU_MEASUREMENT.json")
# Append-only history of every successful on-chip bench (timestamp + git rev).
_HISTORY = os.path.join(_REPO, "BENCH_HISTORY.jsonl")
# Single-flight lock: two processes contending for the one chip is what
# killed the round-3 tunnel. flock blocks the second runner until the
# first finishes (or times out and falls back to CPU).
_LOCKFILE = os.path.join(_REPO, ".bench.lock")

# ResNet50 ImageNet-224 analytic forward FLOPs per image. The commonly
# quoted 4.089e9 counts multiply-ACCUMULATES; the MFU convention (and the
# BERT leg's PaLM-style flops_per_token) counts 2 FLOPs per MAC, so the
# forward pass is 2x that. Backward ~= 2x forward (resnet50_mfu's 3x).
RESNET50_FWD_FLOPS = 2 * 4.089e9

# Bumped when the accounting above changes; stamped on every resnet leg
# record so history consumers can reject stale-convention lines.
RESNET_MFU_CONVENTION = 2


def resnet50_mfu(batch: int, step_s: float, peak: float) -> float:
    """The ONE ResNet50 train-step MFU formula (fwd + ~2x bwd), shared by
    bench_resnet50 and tools/resnet_perf so the convention cannot fork."""
    return 3.0 * RESNET50_FWD_FLOPS * batch / step_s / peak


def _peak_flops(jax, on_tpu: bool) -> float:
    """Per-chip bf16 peak FLOP/s by device generation (MFU convention)."""
    kind = jax.devices()[0].device_kind.lower() if on_tpu else "cpu"
    if "v5 lite" in kind or "v5e" in kind:
        return 197e12
    if "v5p" in kind or "v5" in kind:
        return 459e12
    if "v4" in kind:
        return 275e12
    if "v6" in kind or "trillium" in kind:
        return 918e12
    return 197e12 if on_tpu else 1e12


def _sweep_best(batches, run_leg):
    """Run ``run_leg(batch) -> result`` per batch, keep the best throughput
    (key "_tps"); a leg that raises (HBM OOM at the spill boundary) is
    skipped so the surviving measurements still produce the metric."""
    best = None
    errors = []
    for batch in batches:
        try:
            cur = run_leg(batch)
        except Exception as e:  # noqa: BLE001 - resource exhaustion etc.
            errors.append("batch %s: %s" % (batch, str(e)[:120]))
            continue
        if best is None or cur["_tps"] > best["_tps"]:
            best = cur
    if best is None:
        raise RuntimeError("every sweep leg failed: %s" % "; ".join(errors))
    best.pop("_tps", None)
    return best


def _time_steps(step, args, iters: int) -> float:
    """Time compiled steps with DEVICE-RESIDENT args.

    Inputs are device_put once before the clock starts: the axon tunnel
    moves host->device bytes at ~20 MB/s, so re-transferring a numpy batch
    every step times the tunnel, not the chip (measured: resnet50 batch 128
    = 77 MB/step = 2.8 s/step "compute").  Real training overlaps this
    transfer via the DataLoader's async device_put prefetch, so the honest
    per-step number is compute with staged inputs.
    """
    import jax

    def _sync(loss):
        # host fetch = the synchronization point; a multi-step dispatch
        # returns a [K] loss vector, where the last entry is reported
        arr = np.asarray(getattr(loss, "value", loss), dtype=np.float64)
        return float(arr.reshape(-1)[-1])

    args = tuple(jax.device_put(a) if isinstance(a, np.ndarray) else a
                 for a in args)
    for _ in range(2):  # warmup (includes compile)
        loss = step(*args)
    _sync(loss)
    t0 = time.perf_counter()
    for _ in range(iters):
        loss = step(*args)
    val = _sync(loss)  # block on the last step
    return (time.perf_counter() - t0) / iters, val


def _lm_leg_runner(pt, jax, on_tpu, cfg, batches, seq, iters,
                   shift_labels):
    """Shared causal/masked-LM training leg: TransformerLM + AdamW under
    bf16 O2 (fp32 master weights, loss math fp32 via the amp black list)
    through the donated TrainStep, swept over batch sizes.  Used by the
    bert / gpt-proxy / long-seq legs."""
    from paddle_tpu.jit import TrainStep
    from paddle_tpu.models import TransformerLM, TransformerLMCriterion

    pt.seed(0)
    model = TransformerLM(**cfg, dropout=0.0)
    criterion = TransformerLMCriterion(shift_labels=shift_labels)
    opt = pt.optimizer.AdamW(1e-4, parameters=model.parameters())
    model, opt = pt.amp.decorate(model, opt, level="O2", dtype="bfloat16")

    def loss_fn(m, ids, labels):
        with pt.amp.auto_cast(level="O1", dtype="bfloat16"):
            return criterion(m(ids), labels)

    step = TrainStep(model, loss_fn, opt)
    rng = np.random.RandomState(0)
    flops_tok = model.flops_per_token(seq)

    def leg(batch):
        ids = rng.randint(0, cfg["vocab_size"], (batch, seq)).astype("int32")
        dt, loss = _time_steps(step, (ids, ids), iters)
        tps = batch * seq / dt
        return {"_tps": tps, "tokens_per_sec": tps, "step_time_s": dt,
                "mfu": flops_tok * batch * seq / dt / _peak_flops(jax, on_tpu),
                "batch": batch, "seq": seq, "loss": loss}

    return _sweep_best(batches, leg)


def _cpu_smoke_shrink(cfg, **extra):
    """Shrink a real model config to THE shared CPU-smoke geometry.

    Every CPU-fallback leg must run this one geometry: the legs are
    compared against each other (plain vs speculative decode, decode vs
    serving), and a per-leg copy of these numbers that drifted would
    silently compare different models.  ``extra`` carries the per-leg
    additions (``max_position`` for the decode-family legs)."""
    cfg.update(num_layers=2, hidden_size=128, num_heads=2,
               intermediate_size=512, vocab_size=1024, **extra)
    return cfg


def bench_bert(pt, jax, on_tpu: bool):
    from paddle_tpu.models import bert_base_config

    cfg = bert_base_config()
    if not on_tpu:  # CPU smoke: shrink so the harness itself stays testable
        _cpu_smoke_shrink(cfg)
    # batch 40 was the measured v5e knee (0.4365 MFU); sweep its
    # neighborhood in case layout/memory behavior moved
    batches, seq = ([40, 48, 32], 512) if on_tpu else ([2], 128)
    return _lm_leg_runner(pt, jax, on_tpu, cfg, batches, seq,
                          10 if on_tpu else 3, shift_labels=False)


def bench_bert_multistep(pt, jax, on_tpu: bool):
    """BERT leg dispatched K steps per jitted call (MultiStepTrainStep,
    lax.scan over stacked batches, donated carry).

    Separates per-dispatch transport latency from train-step compute the
    same way tools/ceiling_probe.py's K-step driver does, but as the
    production API: if this leg's per-step throughput materially beats
    the single-step bert leg, the single-step number was
    dispatch-latency-bound through the tunnel and this is the honest
    chip figure (tagged steps_per_call so the two are never conflated).
    """
    from paddle_tpu.jit import MultiStepTrainStep
    from paddle_tpu.models import (TransformerLM, TransformerLMCriterion,
                                   bert_base_config)

    cfg = bert_base_config()
    if on_tpu:
        k, batch, seq, iters = 8, 40, 512, 3
    else:
        _cpu_smoke_shrink(cfg)
        k, batch, seq, iters = 2, 2, 128, 2

    pt.seed(0)
    model = TransformerLM(**cfg, dropout=0.0)
    criterion = TransformerLMCriterion(shift_labels=False)
    opt = pt.optimizer.AdamW(1e-4, parameters=model.parameters())
    model, opt = pt.amp.decorate(model, opt, level="O2", dtype="bfloat16")

    def loss_fn(m, ids, labels):
        with pt.amp.auto_cast(level="O1", dtype="bfloat16"):
            return criterion(m(ids), labels)

    step = MultiStepTrainStep(model, loss_fn, opt, steps_per_call=k)
    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg["vocab_size"], (k, batch, seq)).astype("int32")
    dt, loss = _time_steps(step, (ids, ids), iters)
    per_step = dt / k
    tps = k * batch * seq / dt
    flops_tok = model.flops_per_token(seq)
    return {"tokens_per_sec": tps, "step_time_s": per_step,
            "mfu": flops_tok * batch * seq / per_step / _peak_flops(jax, on_tpu),
            "steps_per_call": k, "batch": batch, "seq": seq, "loss": loss}


def wrap_resnet_remat(model):
    """Wrap each residual block's forward in fleet.utils.recompute so its
    activations are replayed in backward instead of held — the batch-256
    HBM-spill mitigation.  Shared by bench_resnet50 and
    tools/resnet_perf.py (which imports it from here)."""
    from paddle_tpu.distributed.fleet.utils import recompute

    for name, sub in model.named_sublayers():
        if name.startswith("layer") and name.count(".") == 1:
            orig = sub.forward
            sub.forward = (lambda *a, __o=orig, **kw:
                           recompute(__o, *a) if not kw
                           else __o(*a, **kw))
    return model


def bench_resnet50(pt, jax, on_tpu: bool):
    """Config #2: ResNet50, compiled ("static Executor") path + AMP.

    Batch size is swept (per-chip HBM sets the throughput knee; a spilling
    batch collapses per-image speed — measured 6.6s/step at 256 vs
    0.065s/step at 64 on v5e) and the best imgs/sec leg wins; a leg that
    OOMs is skipped by _sweep_best.
    """
    from paddle_tpu.jit import TrainStep
    from paddle_tpu.vision.models import resnet50

    pt.seed(0)
    if on_tpu:
        # sweep layout x batch x remat x s2d-stem: NHWC is the TPU-native
        # conv layout (channels-last lanes); NCHW kept as a fallback leg;
        # the remat leg trades replayed block FLOPs for the HBM that
        # spills at batch 256; s2d rewrites the MXU-hostile 7x7/3ch stem
        legs_cfg = [("NHWC", 128, False, True), ("NHWC", 128, False, False),
                    ("NHWC", 256, True, True), ("NHWC", 64, False, True),
                    ("NCHW", 128, False, False)]
        hw, classes = 224, 1000
        flops_fwd = RESNET50_FWD_FLOPS
    else:
        # the remat/s2d legs keep those paths exercised off-chip too
        legs_cfg = [("NHWC", 4, False, False), ("NHWC", 4, True, True)]
        hw, classes = 32, 10
        flops_fwd = 1e9  # nominal; CPU smoke only checks the harness runs

    steps = {}

    def get_step(fmt, remat, s2d):
        key = (fmt, remat, s2d)
        if key not in steps:
            # one live model at a time: a cached dead-config model would
            # hold params+optimizer state in HBM through later legs and
            # can OOM the comparison leg near the spill boundary
            steps.clear()
            pt.seed(0)
            model = resnet50(num_classes=classes, data_format=fmt,
                             space_to_depth_stem=s2d)
            if remat:
                wrap_resnet_remat(model)
            criterion = pt.nn.CrossEntropyLoss()
            opt = pt.optimizer.Momentum(0.1, parameters=model.parameters())
            model, opt = pt.amp.decorate(model, opt, level="O2",
                                         dtype="bfloat16")

            def loss_fn(m, x, y):
                with pt.amp.auto_cast(level="O1", dtype="bfloat16"):
                    return criterion(m(x), y)

            steps[key] = TrainStep(model, loss_fn, opt)  # donated buffers
        return steps[key]

    rng = np.random.RandomState(0)

    def leg(cfg):
        fmt, batch, remat, s2d = cfg
        imgs = rng.randn(batch, 3, hw, hw).astype("float32")
        labels = rng.randint(0, classes, (batch,)).astype("int64")
        # 12 iters on-chip amortizes the single end-of-loop host fetch
        # (~70 ms tunnel RPC) to noise; see tools/resnet_perf.measure_leg
        dt, loss = _time_steps(get_step(fmt, remat, s2d), (imgs, labels),
                               12 if on_tpu else 2)
        ips = batch / dt
        mfu = (resnet50_mfu(batch, dt, _peak_flops(jax, on_tpu))
               if on_tpu else
               3.0 * flops_fwd * batch / dt / _peak_flops(jax, on_tpu))
        return {
            "_tps": ips,
            "imgs_per_sec": ips,
            "step_time_s": dt,
            "mfu": mfu,
            # legs without the current marker predate the 2-FLOPs-per-MAC
            # accounting fix and understate MFU exactly 2x (see
            # RESNET50_FWD_FLOPS); it disambiguates history lines
            "mfu_convention": RESNET_MFU_CONVENTION,
            "batch": batch,
            "data_format": fmt,
            "remat": remat,
            "s2d_stem": s2d,
            "loss": loss,
        }

    return _sweep_best(legs_cfg, leg)


def bench_mnist(pt, jax, on_tpu: bool):
    """Config #1: MNIST LeNet, dygraph-style train step, single host.

    Tiny model — the number that matters is steps/sec of the full
    imperative train loop (the reference's dygraph MNIST benchmark shape),
    not MFU.  Batch swept; imgs/sec reported.
    """
    from paddle_tpu.jit import TrainStep
    from paddle_tpu.vision.models import LeNet

    pt.seed(0)
    batches = [512, 1024, 2048] if on_tpu else [64]
    model = LeNet()
    criterion = pt.nn.CrossEntropyLoss()
    opt = pt.optimizer.Adam(1e-3, parameters=model.parameters())
    step = TrainStep(model, lambda m, x, y: criterion(m(x), y), opt)
    rng = np.random.RandomState(0)

    def leg(batch):
        imgs = rng.rand(batch, 1, 28, 28).astype("float32")
        labels = rng.randint(0, 10, (batch,)).astype("int64")
        dt, loss = _time_steps(step, (imgs, labels), 20 if on_tpu else 2)
        return {"_tps": batch / dt, "imgs_per_sec": batch / dt,
                "step_time_s": dt, "batch": batch, "loss": loss}

    return _sweep_best(batches, leg)


def bench_mnist_multistep(pt, jax, on_tpu: bool):
    """MNIST LeNet with 32 scanned steps per dispatch: a sub-millisecond
    step is dispatch-latency-bound no matter how inputs are staged, so
    the honest steps/sec for tiny models comes from the multi-step
    driver (tagged steps_per_call; compare against mnist_lenet)."""
    from paddle_tpu.jit import MultiStepTrainStep
    from paddle_tpu.vision.models import LeNet

    pt.seed(0)
    k, batch, iters = (32, 2048, 4) if on_tpu else (4, 64, 2)
    model = LeNet()
    criterion = pt.nn.CrossEntropyLoss()
    opt = pt.optimizer.Adam(1e-3, parameters=model.parameters())
    step = MultiStepTrainStep(model, lambda m, x, y: criterion(m(x), y),
                              opt, steps_per_call=k)
    rng = np.random.RandomState(0)
    imgs = rng.rand(k, batch, 1, 28, 28).astype("float32")
    labels = rng.randint(0, 10, (k, batch)).astype("int64")
    dt, loss = _time_steps(step, (imgs, labels), iters)
    return {"imgs_per_sec": k * batch / dt, "step_time_s": dt / k,
            "steps_per_call": k, "batch": batch, "loss": loss}


def bench_ernie_sharding(pt, jax, on_tpu: bool):
    """Config #4: ERNIE-base fine-tune through the ZeRO stage-2 sharding
    machinery (single-chip timing: the sharding group is the 1-device mesh,
    so the number measures the full stage-2 step — reduce-scatter/all-gather
    degenerate to identity — on the real fine-tune geometry, seq 384)."""
    from jax.sharding import Mesh

    from paddle_tpu.distributed.collective import Group
    from paddle_tpu.distributed.meta_parallel import ShardingOptimizerStage2
    from paddle_tpu.jit import TrainStep
    from paddle_tpu.models import (TransformerForSequenceClassification,
                                   ernie_base_config)

    pt.seed(0)
    cfg = ernie_base_config()
    if on_tpu:
        batches, seq = [32, 48, 64], 384
    else:
        cfg.update(num_layers=2, hidden_size=64, num_heads=4,
                   intermediate_size=128, vocab_size=512, max_position=64)
        batches, seq = [4], 32

    model = TransformerForSequenceClassification(num_classes=3, dropout=0.0,
                                                 **cfg)
    devices = jax.devices()[:1]
    mesh = Mesh(np.array(devices), ("sharding",))
    group = Group(ranks=[0], mesh=mesh, axis_name="sharding")
    opt = ShardingOptimizerStage2(
        pt.optimizer.AdamW(1e-4, parameters=model.parameters()), group=group)
    model, opt = pt.amp.decorate(model, opt, level="O2", dtype="bfloat16")

    def loss_fn(m, ids, types, labels):
        with pt.amp.auto_cast(level="O1", dtype="bfloat16"):
            return pt.nn.functional.cross_entropy(
                m(ids, token_type_ids=types), labels)

    step = TrainStep(model, loss_fn, opt, donate=False)
    rng = np.random.RandomState(0)
    flops_tok = model.backbone.flops_per_token(seq)

    def leg(batch):
        ids = rng.randint(0, cfg["vocab_size"], (batch, seq)).astype("int32")
        types = rng.randint(0, cfg.get("type_vocab_size", 2),
                            (batch, seq)).astype("int32")
        labels = rng.randint(0, 3, (batch,)).astype("int32")
        with mesh:
            dt, loss = _time_steps(step, (ids, types, labels),
                                   8 if on_tpu else 2)
        tps = batch * seq / dt
        return {"_tps": tps, "tokens_per_sec": tps, "step_time_s": dt,
                "mfu": flops_tok * batch * seq / dt / _peak_flops(jax, on_tpu),
                "batch": batch, "seq": seq, "loss": loss}

    return _sweep_best(batches, leg)


def bench_gpt_block(pt, jax, on_tpu: bool):
    """Config #5 proxy: GPT-3 1.3B geometry (hidden 2048, 16 heads, ff 8192,
    causal, 50304 vocab) at a layer count that fits one chip's HBM with
    optimizer state (6 of 24 layers ~ 0.4B params).  The pp x mp *schedule*
    is validated on the 8-device mesh by ``__graft_entry__.dryrun_multichip``
    and the pipeline timing leg in ``tools/pp_timing.py``; one real chip
    cannot host two pipeline stages, so this leg records the on-chip
    per-block training throughput of the same geometry (tokens/s + MFU)."""
    from paddle_tpu.models import gpt_1p3b_config

    cfg = gpt_1p3b_config()
    if on_tpu:
        cfg.update(num_layers=6)
        batches, seq = [8, 16, 4], 1024
    else:
        _cpu_smoke_shrink(cfg)
        batches, seq = [2], 128
    return _lm_leg_runner(pt, jax, on_tpu, cfg, batches, seq,
                          6 if on_tpu else 2, shift_labels=True)


def bench_longseq_flash(pt, jax, on_tpu: bool):
    """Long-context leg: causal LM step at seq 8192 — above the measured
    FLASH_MIN_SEQ crossover, so attention runs through the pallas TPU
    flash kernel (ops/flash_attention.py).  Records tokens/s + MFU for
    the long-sequence regime the ring/Ulysses SP path extends across
    chips (sequence scaling itself needs >1 chip; this is the per-chip
    kernel-path number)."""
    if on_tpu:
        cfg = dict(vocab_size=32000, hidden_size=1024, num_layers=4,
                   num_heads=8, intermediate_size=4096, max_position=8192,
                   causal=True)
        batches, seq = [1, 2], 8192
    else:
        # CPU fallback: flash is TPU-gated anyway, so a long sequence
        # would only burn O(L^2) fallback-attention time; keep it tiny
        cfg = dict(vocab_size=512, hidden_size=128, num_layers=2,
                   num_heads=2, intermediate_size=256, max_position=256,
                   causal=True)
        batches, seq = [1], 256
    return _lm_leg_runner(pt, jax, on_tpu, cfg, batches, seq,
                          4 if on_tpu else 2, shift_labels=True)


def measure_decode_marginal(sess, ids, gen: int, repeats: int = 3) -> dict:
    """THE decode-timing recipe, shared by bench_decode and
    tools/decode_sweep.py so the methodology cannot fork: warm both
    executables, then median-of-N a 1-token generation (isolates the
    prefill term) and a ``gen``-token generation; the DIFFERENCE is pure
    per-token decode time whatever the fixed dispatch overhead — the
    marginal discipline of tools/ceiling_probe.py, with the same
    median-of-N guard (a difference of single samples can go negative on
    one scheduler hiccup).  Spreads are recorded as the noise floor."""
    if gen < 2:
        raise ValueError(
            "measure_decode_marginal needs gen >= 2 (the marginal is a "
            "difference against the 1-token generation), got %d" % gen)
    sess.generate(ids, 2)  # compile prefill bucket + decode step
    one, full = [], []
    for _ in range(repeats):
        t0 = time.perf_counter()
        sess.generate(ids, 1)
        one.append(time.perf_counter() - t0)
        t0 = time.perf_counter()
        sess.generate(ids, gen)
        full.append(time.perf_counter() - t0)
    t_one, t_full = float(np.median(one)), float(np.median(full))
    per_tok = (t_full - t_one) / (gen - 1)
    if per_tok < 1e-9:
        # median-of-N shrinks but cannot eliminate the hiccup hazard; a
        # non-positive (or sub-nanosecond: no real decode step is that
        # fast) marginal means noise exceeded the signal, and a garbage
        # or div-by-zero tokens/s must never reach a report
        raise RuntimeError(
            "implausible decode marginal %.3g s/token (t_one=%.4g, "
            "t_full=%.4g): timing noise exceeded the signal; increase "
            "gen or repeats" % (per_tok, t_one, t_full))
    return {
        "prefill_s": round(t_one, 5),
        "total_s": round(t_full, 5),
        # raw, not display-rounded: callers divide by this for tokens/s
        "per_token_s": per_tok,
        # µs twin survives the record's 4-decimal _round_tree on fast chips
        "per_token_us": round(per_tok * 1e6, 3),
        "spread_one_s": round(max(one) - min(one), 6),
        "spread_full_s": round(max(full) - min(full), 6),
    }


DECODE_BLOCK_SIZE = 32  # default KV block for the paged-layout legs


def bench_decode(pt, jax, on_tpu: bool):
    """L7 serving leg: KV-cached autoregressive decode (jit.DecodeSession,
    prefill 512 + 128 generated) at batch 1 and 8, for BOTH cache
    layouts (dense preallocation vs paged block-table) and BOTH cache
    dtypes (fp32 vs quantized int8) — tokens/s/chip of the steady-state
    decode step, the number a token-serving deployment lives on.  Every
    timed sub-leg records its ``cache_layout`` AND ``cache_dtype`` plus
    the KV-cache bytes reachable per step at the leg's occupancy (the
    _leg_promotable gate REJECTS decode legs missing either stamp, so a
    paged-vs-dense or int8-vs-fp32 number can never be presented
    without its provenance); ``kv_bytes_by_occupancy`` quantifies the
    paged HBM win AND the int8 byte reduction across fill levels
    instead of asserting them, and ``block_size_sweep`` records paged
    tokens/s against the block-size axis.  Timing via
    measure_decode_marginal (median-of-3 marginal decode time).  The
    prompt upload happens inside the timed generate calls, so this leg
    does NOT claim input_staged; its transfer bias is bounded in
    transfer_note instead (the gate accepts either)."""
    from paddle_tpu.inference.generation import kv_reachable_bytes
    from paddle_tpu.jit import DecodeSession
    from paddle_tpu.models import TransformerLM, gpt_1p3b_config

    prefill, gen = 512, 128
    cfg = gpt_1p3b_config()
    if on_tpu:
        cfg.update(num_layers=6)  # the one-chip GPT geometry (gpt leg)
    else:
        _cpu_smoke_shrink(cfg, max_position=1024)

    pt.seed(0)
    model = TransformerLM(**cfg, dropout=0.0)
    max_len = prefill + gen
    dims = dict(max_len=max_len, num_layers=cfg["num_layers"],
                num_heads=cfg["num_heads"],
                head_dim=cfg["hidden_size"] // cfg["num_heads"])
    rng = np.random.RandomState(0)
    legs = {}
    best_tps = 0.0
    compile_counts = {}
    for layout in ("dense", "paged"):
        for cache_dtype in ("float32", "int8"):
            sess = DecodeSession(model, max_len=max_len, buckets=[prefill],
                                 cache_layout=layout,
                                 block_size=DECODE_BLOCK_SIZE,
                                 cache_dtype=cache_dtype)
            tag = "fp32" if cache_dtype == "float32" else cache_dtype
            for batch in (1, 8):
                ids = rng.randint(0, cfg["vocab_size"],
                                  (batch, prefill)).astype("int32")
                m = measure_decode_marginal(sess, ids, gen)
                tps = batch / m["per_token_s"]
                # compiler-reported cost-model columns next to the
                # measured ones (docs/DESIGN.md §5h): what XLA says one
                # decode step costs, per token, from the EXACT
                # executable the timed loop ran (last_cost = this
                # batch's decode step, the most recent compile) — the
                # honest basis for "are we at the hardware roofline"
                # questions.  Missing analyses stamp None, never a
                # fake 0 a later report would flag as a regression
                cost = sess._decode_jit.last_cost() or {}
                flops = cost.get("flops")
                nbytes = cost.get("bytes_accessed")
                bpt = None if nbytes is None else nbytes / batch
                legs["%s_%s_batch%d" % (layout, tag, batch)] = dict(
                    m, cache_layout=layout, cache_dtype=cache_dtype,
                    decode_route=sess.route,
                    decode_tokens_per_sec=round(tps, 1),
                    cost_flops_per_token=(None if flops is None
                                          else flops / batch),
                    cost_bytes_per_token=bpt,
                    # measured tok/s x compiler-stated bytes/token: the
                    # HBM bandwidth the decode step actually sustains —
                    # the roofline column the fused kernel (§5l) exists
                    # to move, stamped so bench_report can gate it
                    bandwidth_util_bytes_per_sec=(
                        None if bpt is None else round(tps * bpt, 1)),
                    cost_hbm_reserved_bytes=cost.get(
                        "hbm_reserved_bytes"),
                    cost_kv_cache_bytes=cost.get("kv_cache_bytes"),
                    kv_reachable_bytes=kv_reachable_bytes(
                        [max_len] * batch, layout=layout,
                        block_size=DECODE_BLOCK_SIZE, dtype=cache_dtype,
                        **dims))
                best_tps = max(best_tps, tps)
            compile_counts["%s_%s" % (layout, tag)] = sess.compile_counts()
    if on_tpu:
        # kernel-routed sub-legs (compiled pallas, TPU only — off-TPU
        # the forced route runs the INTERPRETER, whose wall time
        # measures the interpreter): the paged fused kernel against the
        # composition legs above at the big-batch point, both dtypes.
        # _leg_promotable refuses these without the bandwidth stamp.
        for cache_dtype in ("float32", "int8"):
            sess = DecodeSession(model, max_len=max_len,
                                 buckets=[prefill],
                                 cache_layout="paged",
                                 block_size=DECODE_BLOCK_SIZE,
                                 cache_dtype=cache_dtype, route="pallas")
            tag = "fp32" if cache_dtype == "float32" else cache_dtype
            ids = rng.randint(0, cfg["vocab_size"],
                              (8, prefill)).astype("int32")
            m = measure_decode_marginal(sess, ids, gen)
            tps = 8 / m["per_token_s"]
            cost = sess._decode_jit.last_cost() or {}
            nbytes = cost.get("bytes_accessed")
            bpt = None if nbytes is None else nbytes / 8
            legs["paged_%s_batch8_pallas" % tag] = dict(
                m, cache_layout="paged", cache_dtype=cache_dtype,
                decode_route="pallas",
                decode_tokens_per_sec=round(tps, 1),
                cost_bytes_per_token=bpt,
                bandwidth_util_bytes_per_sec=(
                    None if bpt is None else round(tps * bpt, 1)),
                kv_reachable_bytes=kv_reachable_bytes(
                    [max_len] * 8, layout="paged",
                    block_size=DECODE_BLOCK_SIZE, dtype=cache_dtype,
                    **dims))
            best_tps = max(best_tps, tps)
            compile_counts["paged_%s_pallas" % tag] = \
                sess.compile_counts()
    # the paged win AND the int8 byte reduction quantified across fill
    # levels: reachable KV bytes at batch-8 occupancy fractions of
    # max_len (dense pins the full slab whatever the occupancy; paged
    # maps only ceil(tokens/bs) blocks; the *_int8 twins count int8 K/V
    # plus the riding fp32 per-head scales, so the ~2x-vs-bf16 /
    # ~4x-vs-fp32 reduction is in the artifact, not just the prose)
    occupancy = []
    for frac in (0.125, 0.25, 0.5, 0.75, 1.0):
        tokens = max(1, int(max_len * frac))
        occupancy.append({
            "tokens_per_slot": tokens, "slots": 8,
            "dense_bytes": kv_reachable_bytes([tokens] * 8,
                                              layout="dense", **dims),
            "paged_bytes": kv_reachable_bytes(
                [tokens] * 8, layout="paged",
                block_size=DECODE_BLOCK_SIZE, **dims),
            "dense_bytes_int8": kv_reachable_bytes(
                [tokens] * 8, layout="dense", dtype="int8", **dims),
            "paged_bytes_int8": kv_reachable_bytes(
                [tokens] * 8, layout="paged",
                block_size=DECODE_BLOCK_SIZE, dtype="int8", **dims)})
    # tokens/s against the block-size axis (batch 1, short generation:
    # the axis's effect is on the gather/scatter addressing, visible
    # without a long run) — the CPU record the ROADMAP item asks for,
    # and the same axis tools/decode_sweep.py sweeps at scale
    sweep_gen = min(gen, 32)
    sweep_ids = rng.randint(0, cfg["vocab_size"],
                            (1, prefill)).astype("int32")
    block_sweep = []
    for bs in (16, 32, 64, 128):
        s = DecodeSession(model, max_len=max_len, buckets=[prefill],
                          cache_layout="paged", block_size=bs)
        m = measure_decode_marginal(s, sweep_ids, sweep_gen)
        block_sweep.append(dict(
            m, cache_layout="paged", cache_dtype="float32", block_size=bs,
            decode_tokens_per_sec=round(1.0 / m["per_token_s"], 1)))
    out = {
        "tokens_per_sec": best_tps,
        "prefill": prefill,
        "generated": gen,
        "cache_layouts": ["dense", "paged"],
        "cache_dtypes": ["float32", "int8"],
        "block_size": DECODE_BLOCK_SIZE,
        "kv_bytes_by_occupancy": occupancy,
        "block_size_sweep": block_sweep,
        "compile_counts": compile_counts,
        # prompt ids are uploaded INSIDE the timed region: never claim
        # the staged-input stamp (the blanket stamper respects this)
        "input_staged": False,
        "transfer_note": (
            "prompt upload (batch x 512 int32, <=16 KB) sits in the "
            "prefill term, which the marginal differencing SUBTRACTS "
            "out; the per-token figure's only host traffic is the "
            "sampled [batch] token ids (4 B/row) fetched per step"),
    }
    out.update(legs)
    return out


def bench_decode_ssm(pt, jax, on_tpu: bool):
    """L7 serving leg for the O(1)-cache model class (docs §5p):
    KV-cached autoregressive decode of an ``SSMLM`` through the SAME
    ``DecodeSession`` the transformer decode leg times — same prefill/
    generation lengths, same ``measure_decode_marginal`` methodology,
    same hidden size / layer count as the transformer leg's geometry,
    so the two legs' tokens/s compare like with like.

    The model-class claim is stamped NUMERICALLY, not asserted:
    ``slots_per_gb`` (how many concurrent decode slots one GB of HBM
    holds when a slot's whole state is ``layers x d_state`` fp32) next
    to ``slots_per_gb_transformer`` (the same GB holding dense fp32
    K/V at max_len for the transformer leg's geometry) and their
    ratio.  ``_leg_promotable`` REJECTS a decode_ssm leg whose timed
    sub-legs miss the numeric ``slots_per_gb`` stamp — an O(1)-cache
    tokens/s without its capacity figure cannot say what the constant
    state bought."""
    from paddle_tpu.jit import DecodeSession
    from paddle_tpu.models import gpt_1p3b_config
    from paddle_tpu.nn import SSMLM

    prefill, gen = 512, 128
    # the transformer decode leg's geometry, reused so hidden/layers
    # (and therefore the capacity comparison) match that leg exactly
    cfg = gpt_1p3b_config()
    if on_tpu:
        cfg.update(num_layers=6)
    else:
        _cpu_smoke_shrink(cfg, max_position=1024)
    max_len = prefill + gen
    pt.seed(0)
    model = SSMLM(vocab_size=cfg["vocab_size"],
                  hidden_size=cfg["hidden_size"],
                  num_layers=cfg["num_layers"], dropout=0.0)
    state_bytes_per_slot = cfg["num_layers"] * model.d_state * 4
    # dense fp32 K/V at max_len for the SAME geometry: what one
    # transformer slot pins in the baseline layout (2 = K and V)
    kv_bytes_per_slot = 2 * cfg["num_layers"] * cfg["hidden_size"] \
        * max_len * 4
    slots_per_gb = (1 << 30) // state_bytes_per_slot
    slots_per_gb_tf = (1 << 30) // kv_bytes_per_slot
    rng = np.random.RandomState(0)
    sess = DecodeSession(model, max_len=max_len, buckets=[prefill],
                         cache_layout="recurrent")
    legs = {}
    best_tps = 0.0
    for batch in (1, 8):
        ids = rng.randint(0, cfg["vocab_size"],
                          (batch, prefill)).astype("int32")
        m = measure_decode_marginal(sess, ids, gen)
        tps = batch / m["per_token_s"]
        cost = sess._decode_jit.last_cost() or {}
        flops = cost.get("flops")
        nbytes = cost.get("bytes_accessed")
        legs["recurrent_fp32_batch%d" % batch] = dict(
            m, cache_layout="recurrent", cache_dtype="float32",
            decode_route=sess.route,
            decode_tokens_per_sec=round(tps, 1),
            cost_flops_per_token=(None if flops is None
                                  else flops / batch),
            cost_bytes_per_token=(None if nbytes is None
                                  else nbytes / batch),
            cost_kv_cache_bytes=cost.get("kv_cache_bytes"),
            state_bytes_per_slot=state_bytes_per_slot,
            slots_per_gb=slots_per_gb)
        best_tps = max(best_tps, tps)
    out = {
        "tokens_per_sec": best_tps,
        "prefill": prefill,
        "generated": gen,
        "cache_layouts": ["recurrent"],
        "cache_dtypes": ["float32"],
        "d_state": model.d_state,
        "num_layers": cfg["num_layers"],
        "hidden_size": cfg["hidden_size"],
        "state_bytes_per_slot": state_bytes_per_slot,
        "kv_bytes_per_slot_transformer": kv_bytes_per_slot,
        "slots_per_gb": slots_per_gb,
        "slots_per_gb_transformer": slots_per_gb_tf,
        "slots_per_gb_ratio": round(slots_per_gb / slots_per_gb_tf, 1),
        "compile_counts": sess.compile_counts(),
        "input_staged": False,
        "transfer_note": (
            "prompt upload (batch x 512 int32, <=16 KB) sits in the "
            "prefill term, which the marginal differencing SUBTRACTS "
            "out; the per-token figure's only host traffic is the "
            "sampled [batch] token ids (4 B/row) fetched per step"),
    }
    out.update(legs)
    return out


def _histogram_quantile(hist, q: float):
    """A serving Histogram's quantile as a JSON-safe number: the bucket
    upper-bound estimate, None when the histogram is empty or the
    quantile overflowed the largest bucket (inf is not valid JSON)."""
    v = hist.quantile(q)
    if v is None or v != v or v == float("inf"):
        return None
    return round(float(v), 6)


def bench_serving(pt, jax, on_tpu: bool):
    """L7 serving-ENGINE leg: p50/p95 TTFT and sustained tokens/s
    through ``serving.ServingEngine.pump()`` at 1 and 8 slots — the
    end-to-end scheduler price (admission, lifecycle, streaming,
    metrics hooks) ON TOP of the raw decode step bench_decode times.
    Driven by the synchronous pump() mode, so the leg is
    single-threaded and measures the same code path the deterministic
    tests pin.  Sub-legs are stamped with ``cache_layout`` AND
    ``cache_dtype`` exactly like the decode leg, and the
    _leg_promotable gate rejects serving legs missing either stamp.
    TTFT percentiles come from the per-request StreamStatus timings
    (exact), not the bucketed histogram; inter-token latency p50/p95
    come from the engine's ``serving_inter_token_seconds`` histogram
    (bucket upper-bound estimates — the per-gap timestamps are not
    retained per request, and the bucketed quantile is the same number
    a Prometheus dashboard would show)."""
    from paddle_tpu.models import TransformerLM, gpt_1p3b_config
    from paddle_tpu.serving import ServingEngine

    prefill, gen = (512, 64) if on_tpu else (32, 8)
    cfg = gpt_1p3b_config()
    if on_tpu:
        cfg.update(num_layers=6)  # the one-chip GPT geometry
    else:
        _cpu_smoke_shrink(cfg, max_position=1024)
    pt.seed(0)
    model = TransformerLM(**cfg, dropout=0.0)
    rng = np.random.RandomState(0)
    max_len = prefill + gen
    out = {
        "prefill": prefill,
        "generated": gen,
        "input_staged": False,
        "transfer_note": (
            "prompt upload rides inside the prefill term exactly as in "
            "the decode leg; the per-token host traffic is the sampled "
            "token ids plus the host-side scheduler bookkeeping this "
            "leg exists to price"),
    }
    best_tps = 0.0
    for slots in (1, 8):
        engine = ServingEngine(model, max_len=max_len, slots=slots,
                               buckets=[prefill], max_queue=4 * slots)
        # warm both executables OUTSIDE the timed region (a cold-compile
        # TTFT measures XLA, not the scheduler)
        engine.submit(rng.randint(0, cfg["vocab_size"],
                                  (prefill,)).astype("int32"), 2)
        while engine.pump(8):
            pass
        # the warmup request's token1->token2 gap CONTAINS the decode
        # compile and was observed into the engine-lifetime inter-token
        # histogram; reset it so itl_p50/p95 honor the warm-outside-the-
        # timed-region rule (TTFT needs no reset: it reads per-request
        # StreamStatus timings of the timed requests only)
        engine.metrics.histogram("serving_inter_token_seconds").reset()
        prompts = [rng.randint(0, cfg["vocab_size"],
                               (prefill,)).astype("int32")
                   for _ in range(2 * slots)]
        t0 = time.perf_counter()
        streams = [engine.submit(p, gen) for p in prompts]
        while engine.pump(16):
            pass
        wall = time.perf_counter() - t0
        statuses = [s.result(timeout_s=0) for s in streams]
        ttfts = [st.ttft_s for st in statuses]
        toks = sum(st.new_tokens for st in statuses)
        tps = toks / wall
        stats = engine.cache_stats()
        itl = engine.metrics.histogram("serving_inter_token_seconds")
        # the engine's compiler-reported cost model (jit.aot via
        # ServingEngine.cost_report) stamped beside the measured
        # figures: per-token FLOPs/bytes and the step executable's HBM
        # reservation, from the artifact this leg actually ran
        cost = engine.cost_report().get("derived") or {}
        bpt = cost.get("bytes_per_token")
        out["batch%d" % slots] = {
            "slots": slots,
            "requests": len(prompts),
            "cache_layout": stats["cache_layout"],
            "cache_dtype": stats["cache_dtype"],
            "decode_route": stats.get("decode_route", "auto"),
            "kv_resident_bytes": stats["pool_bytes"],
            "cost_flops_per_token": cost.get("flops_per_token"),
            "cost_bytes_per_token": bpt,
            # sustained HBM bandwidth (tok/s x compiler bytes/token) —
            # the §5l roofline column; gated for kernel-routed legs
            "bandwidth_util_bytes_per_sec": (
                None if bpt is None else round(tps * bpt, 1)),
            "cost_hbm_reserved_bytes": cost.get("hbm_reserved_bytes"),
            "ttft_p50_s": round(float(np.percentile(ttfts, 50)), 5),
            "ttft_p95_s": round(float(np.percentile(ttfts, 95)), 5),
            "itl_p50_s": _histogram_quantile(itl, 0.5),
            "itl_p95_s": _histogram_quantile(itl, 0.95),
            "tokens_per_sec": round(tps, 1),
            "wall_s": round(wall, 4),
        }
        best_tps = max(best_tps, tps)
    out["tokens_per_sec"] = round(best_tps, 1)
    # tracing price: the SAME traffic through the (warmed) slots=8
    # engine with the flight recorder ON vs OFF — the §5g tracing
    # contract says the recorder must be effectively free on the tick
    # path, and this stamp is where that claim is measured instead of
    # asserted (min-of-2 per mode to shave scheduler noise;
    # _leg_promotable refuses serving legs whose overhead exceeds 3%)
    from paddle_tpu.serving import trace as serving_trace

    def _traffic_wall(tracing: bool) -> float:
        tracer = serving_trace.Tracer(capacity=4096) if tracing else None
        if tracer is not None:
            serving_trace.install(tracer)
        try:
            t0 = time.perf_counter()
            streams = [engine.submit(p, gen) for p in prompts]
            while engine.pump(16):
                pass
            for s in streams:
                s.result(timeout_s=0)
            return time.perf_counter() - t0
        finally:
            if tracer is not None:
                serving_trace.uninstall()
    off_wall = min(_traffic_wall(False), _traffic_wall(False))
    on_wall = min(_traffic_wall(True), _traffic_wall(True))
    out["trace_overhead_pct"] = round(
        max(0.0, (on_wall - off_wall) / off_wall * 100.0), 2)
    return out


def bench_serving_faults(pt, jax, on_tpu: bool):
    """L7 robustness leg: the PRICE of request-level recovery.

    Runs the same traffic twice through ``serving.ServingEngine`` — once
    clean, once with a scripted transient fault injected into the
    batched pool step (``serving.faults``) — and stamps what the
    recovery machinery costs and what it preserves:

    - ``recovery_wall_s``: wall time of the faulted tick (pool rebuild +
      resubmit of every victim) PLUS the pumping until every survivor
      has decoded a post-recovery token — the honest time-to-first-
      recovered-token, synced by the pool's own per-tick host download;
    - ``tokens_lost``: mismatched-or-missing tokens of surviving greedy
      requests vs the fault-free run.  MUST be 0 — greedy recovery is
      token-identical by the O(1)-cache contract, and the
      ``_leg_promotable`` gate structurally refuses to promote a
      serving_faults leg that lost tokens;
    - the recovery counters, so the stamped number says how many
      requests the wall time covered.

    Sub-legs carry cache_layout/cache_dtype stamps like every serving
    leg (the gate rejects them otherwise)."""
    from paddle_tpu.models import TransformerLM, gpt_1p3b_config
    from paddle_tpu.serving import ServingEngine, faults

    prefill, gen = (512, 32) if on_tpu else (16, 8)
    slots = 4
    cfg = gpt_1p3b_config()
    if on_tpu:
        cfg.update(num_layers=6)
    else:
        _cpu_smoke_shrink(cfg, max_position=1024)
    pt.seed(0)
    model = TransformerLM(**cfg, dropout=0.0)
    rng = np.random.RandomState(0)
    max_len = prefill + gen
    prompts = [rng.randint(0, cfg["vocab_size"],
                           (prefill,)).astype("int32")
               for _ in range(2 * slots)]

    def fresh_engine():
        # TWO prefill buckets: `prefill` serves admission, `max_len`
        # serves RECOVERY — a resubmitted victim re-prefills
        # prompt+committed, which outgrows the admission bucket (the
        # bucket-coverage requirement of docs/DESIGN.md §5f)
        return ServingEngine(model, max_len=max_len, slots=slots,
                             buckets=[prefill, max_len],
                             max_queue=4 * slots,
                             cache_layout="paged", block_size=32)

    # fault-free reference (also warms every executable, so the faulted
    # run's recovery wall time measures RECOVERY, not XLA)
    engine = fresh_engine()
    streams = [engine.submit(p, gen, request_id="req-%d" % i)
               for i, p in enumerate(prompts)]
    while engine.pump(16):
        pass
    want = {s.request_id: s.result(timeout_s=0).tokens for s in streams}

    engine = fresh_engine()
    # warm the recovery bucket OUTSIDE the timed region (a cold-compile
    # recovery would measure XLA, not the rebuild+re-prefill): one
    # request long enough to prefill through the max_len bucket
    warm = engine.submit(rng.randint(0, cfg["vocab_size"],
                                     (max_len - 2,)).astype("int32"), 2)
    while engine.pump(8):
        pass
    assert warm.result(timeout_s=0).state == "DONE"
    fault_after = 3  # let the pool reach steady state first
    plane = faults.FaultPlane([faults.FaultSpec(
        "pool.step", error=faults.TransientInjectedFault,
        after=fault_after, times=1)])
    with faults.injected(plane):
        streams = [engine.submit(p, gen, request_id="req-%d" % i)
                   for i, p in enumerate(prompts)]
        engine.pump(fault_after)   # clean steady-state ticks
        tokens_before = int(engine.metrics.snapshot()[
            "serving_tokens_emitted_total"])
        live_before = engine.live_requests
        t0 = time.perf_counter()
        engine.pump(1)             # the tick that faults AND recovers
        # ...then pump until every survivor has emitted a post-recovery
        # token: each recovered request re-prefills (emitting one), so
        # token progress >= survivors means recovery is fully paid for
        while engine.live_requests and int(engine.metrics.snapshot()[
                "serving_tokens_emitted_total"]) - tokens_before \
                < live_before:
            if not engine.pump(1):
                break
        recovery_wall = time.perf_counter() - t0
        while engine.pump(16):
            pass
    statuses = [s.result(timeout_s=0) for s in streams]
    snap = engine.metrics.snapshot()
    stats = engine.cache_stats()
    tokens_lost = 0
    for st in statuses:
        if st.state != "DONE":
            continue  # non-survivors are counted via the failed counter
        ref = want[st.request_id]
        got = np.asarray(st.tokens)
        tokens_lost += max(0, len(ref) - len(got)) + int(
            (got[:len(ref)] != ref[:len(got)]).sum())
    out = {
        "prefill": prefill,
        "generated": gen,
        "slots": slots,
        "input_staged": False,
        "transfer_note": (
            "recovery wall time is host-side rebuild + re-prefill; the "
            "re-prefill's prompt re-upload IS the recovery cost being "
            "measured, synced by the pool's per-tick token download"),
        "faulted": {
            "cache_layout": stats["cache_layout"],
            "cache_dtype": stats["cache_dtype"],
            "requests": len(prompts),
            "recovery_wall_s": round(recovery_wall, 4),
            "tokens_lost": tokens_lost,
            "requests_recovered": int(
                snap["serving_requests_recovered_total"]),
            "requests_failed": int(snap["serving_requests_failed_total"]),
            "recoveries": int(snap["serving_recoveries_total"]),
            "survivors": sum(1 for st in statuses if st.state == "DONE"),
            "blocks_reclaimed": stats["mapped_blocks"] == 0,
        },
    }
    return out


def bench_serving_restart(pt, jax, on_tpu: bool):
    """L7 durability leg: the recovery-time objective of crash-durable
    serving (docs/DESIGN.md §5m) — what a kill-and-adopt restart COSTS
    and what it preserves.

    The same traffic runs three ways: a clean reference (also the warm
    pass), a journaled engine A that is hard-ABANDONED mid-decode with
    one victim parked in the disk spill tier (the in-process stand-in
    for SIGKILL — the real subprocess kill is the slow-marked test in
    tests/test_durable_serving.py), and a fresh engine B that adopts
    A's journal + spill directory.  Stamps:

    - ``restore_rto_s``: restore() (journal read, fingerprint check,
      replay, resubmit/adopt, compaction) PLUS pumping until every
      replayed survivor has decoded a post-restore token — the honest
      restore-time-to-first-recovered-token, synced by the pool's own
      per-tick host download;
    - ``requests_replayed`` / ``adopted_from_spill`` /
      ``tokens_replayed``: what the RTO covered (``_leg_promotable``
      refuses a leg that replayed nothing — an RTO over an empty
      journal measured file I/O, not recovery);
    - ``tokens_lost``: mismatched-or-missing tokens of restored greedy
      requests vs the uninterrupted run.  MUST be 0 — byte-identical
      replay is the §5m contract, and the gate structurally refuses a
      lossy leg."""
    import shutil
    import tempfile

    from paddle_tpu.models import TransformerLM, gpt_1p3b_config
    from paddle_tpu.serving import ServingEngine

    prefill, gen = (512, 32) if on_tpu else (16, 8)
    slots = 4
    cfg = gpt_1p3b_config()
    if on_tpu:
        cfg.update(num_layers=6)
    else:
        _cpu_smoke_shrink(cfg, max_position=1024)
    pt.seed(0)
    model = TransformerLM(**cfg, dropout=0.0)
    rng = np.random.RandomState(0)
    max_len = prefill + gen
    prompts = [rng.randint(0, cfg["vocab_size"],
                           (prefill,)).astype("int32")
               for _ in range(2 * slots)]
    workdir = tempfile.mkdtemp(prefix="bench-restart-")
    jpath = os.path.join(workdir, "requests.journal")
    spill_dir = os.path.join(workdir, "spill")

    def fresh_engine(journal=None):
        # TWO prefill buckets, same §5f bucket-coverage reasoning as
        # the faults leg: `prefill` serves admission, `max_len` serves
        # the restore resubmits (prompt+committed outgrows admission)
        return ServingEngine(model, max_len=max_len, slots=slots,
                             buckets=[prefill, max_len],
                             max_queue=4 * slots,
                             cache_layout="paged", block_size=32,
                             spill_tier="disk", spill_dir=spill_dir,
                             journal_path=journal)

    def submit_all(engine):
        # mixed-priority traffic, lows FIRST and already decoding when
        # the highs arrive: the preempted low victim then stays PARKED
        # behind the high-priority queue at crash time, so the restore
        # prices the spill-adoption path, not just resubmits
        streams = [engine.submit(p, gen, request_id="req-%d" % i,
                                 priority="low")
                   for i, p in enumerate(prompts[:2])]
        engine.pump(2)
        streams += [engine.submit(p, gen, request_id="req-%d" % (i + 2),
                                  priority="high")
                    for i, p in enumerate(prompts[2:])]
        return streams

    try:
        # clean reference on identical traffic (warms every executable)
        engine = fresh_engine()
        streams = submit_all(engine)
        while engine.pump(16):
            pass
        want = {s.request_id: s.result(timeout_s=0).tokens
                for s in streams}

        # engine A: journaled, one low victim spilled to disk, then
        # hard-abandoned mid-decode (no drain, no shutdown, no flush
        # beyond the per-tick WAL discipline)
        engine_a = fresh_engine(journal=jpath)
        streams = submit_all(engine_a)
        engine_a.preempt()   # the low victim, parked behind the highs
        engine_a.pump(2)
        live_at_crash = engine_a.live_requests
        del engine_a, streams

        # engine B: fresh engine, same weights; its OWN warm traffic
        # compiles both buckets OUTSIDE the timed region (the RTO must
        # price replay, never XLA)
        engine = fresh_engine(journal=jpath)
        for warm_len in (max_len - 2, 4):
            engine.submit(rng.randint(0, cfg["vocab_size"],
                                      (warm_len,)).astype("int32"), 2)
            while engine.pump(8):
                pass
        counts_before = engine.compile_counts()
        t0 = time.perf_counter()
        summary = engine.restore(jpath)
        # this traffic cannot legitimately finish AT restore (no EOS
        # id, budgets unexhausted at crash): anything finalized there
        # escaped the tokens_lost loop below, so it must be zero or
        # the leg is invalid
        if summary["finished_at_restore"]:
            raise RuntimeError(
                "serving_restart: %d requests finalized during "
                "restore on traffic that cannot finish there — "
                "loss accounting would be blind to them"
                % (summary["finished_at_restore"],))
        restored = {rid: rec.stream
                    for rid, rec in engine._live.items()}
        # ...pump until EVERY replayed survivor decoded a POST-restore
        # token — per-request progress, not an aggregate count: the
        # active slots would satisfy an aggregate threshold ticks
        # before the parked disk-spill victim resumes, and its page-in
        # is exactly the adopted-path cost this RTO must price
        base = {rid: len(rec.tokens)
                for rid, rec in engine._live.items()}
        while any(rid in engine._live
                  and len(engine._live[rid].tokens) <= n
                  for rid, n in base.items()):
            if not engine.pump(1):
                break
        restore_rto = time.perf_counter() - t0
        while engine.pump(16):
            pass
        tokens_lost = 0
        survivors = 0
        for rid, s in restored.items():
            st = s.result(timeout_s=0)
            # EVERY restored request is accounted, whatever its state:
            # a survivor that finalizes FAILED after restore lost its
            # whole remaining reference stream — excluding it would
            # let a broken resubmit path stamp tokens_lost == 0
            if st.state == "DONE":
                survivors += 1
            ref = want[rid]
            got = np.asarray(st.tokens)
            tokens_lost += max(0, len(ref) - len(got)) + int(
                (got[:len(ref)] != ref[:len(got)]).sum())
        snap = engine.metrics.snapshot()
        stats = engine.cache_stats()
        return {
            "prefill": prefill,
            "generated": gen,
            "slots": slots,
            "input_staged": False,
            "transfer_note": (
                "restore RTO is host-side journal replay + re-prefill "
                "(plus spill-file page-in for the adopted victim); the "
                "re-prefill's prompt re-upload IS the recovery cost "
                "being measured, synced by the pool's per-tick token "
                "download"),
            "restart": {
                "cache_layout": stats["cache_layout"],
                "cache_dtype": stats["cache_dtype"],
                "requests": len(prompts),
                "live_at_crash": live_at_crash,
                "restore_rto_s": round(restore_rto, 4),
                "restore_call_s": round(summary["restore_s"], 4),
                "requests_replayed": int(
                    snap["serving_journal_replayed_total"]),
                "adopted_from_spill": summary["adopted_from_spill"],
                "finished_at_restore": summary["finished_at_restore"],
                "tokens_replayed": summary["tokens_replayed"],
                "journal_records": summary["records"],
                "tokens_lost": tokens_lost,
                "survivors": survivors,
                "no_new_compiles": engine.compile_counts()
                == counts_before,
            },
        }
    finally:
        shutil.rmtree(workdir, ignore_errors=True)


def bench_serving_prefix(pt, jax, on_tpu: bool):
    """L7 prefix-sharing leg: zipf-distributed prompts over a small
    prefix corpus — the real traffic shape (shared system prompts /
    few-shot prefixes) — through the paged engine with chunked prefill,
    SHARING ON vs OFF (off = identical traffic and chunking, prefix
    index disabled), stamping what the feature claims:

    - ``prefix_hit_rate`` and the cumulative blocks/tokens matched
      (plus their byte value — prefill work and HBM the index saved);
    - TTFT p50/p95 per mode: a hit skips straight past the matched
      prefix, so first tokens arrive whole chunks earlier;
    - the PR 10 SLO proof: both modes run under a TTFT objective whose
      threshold is calibrated on a sharing-off probe run, and the leg
      stamps each mode's burn rates — sharing landing should DROP the
      burn on the same traffic.

    ``_leg_promotable`` structurally refuses a serving_prefix leg whose
    sharing-on sub-leg is missing the ``prefix_hit_rate`` stamp (a
    number that cannot say whether the index actually fired measures
    nothing), and the usual cache layout/dtype stamps apply."""
    from paddle_tpu.inference.generation import kv_reachable_bytes
    from paddle_tpu.models import TransformerLM, gpt_1p3b_config
    from paddle_tpu.serving import ServingEngine
    from paddle_tpu.serving.slo import Objective, SLOTracker

    cfg = gpt_1p3b_config()
    if on_tpu:
        cfg.update(num_layers=6)
        prefix_len, suffix_len, gen = 256, 64, 32
        block, chunk, slots = 32, 64, 4
        n_requests, n_prefixes = 24, 4
    else:
        _cpu_smoke_shrink(cfg, max_position=1024)
        prefix_len, suffix_len, gen = 48, 8, 4
        block, chunk, slots = 8, 16, 2
        n_requests, n_prefixes = 10, 3
    max_len = prefix_len + suffix_len + gen
    pt.seed(0)
    model = TransformerLM(**cfg, dropout=0.0)
    rng = np.random.RandomState(0)
    corpus = [rng.randint(0, cfg["vocab_size"],
                          (prefix_len,)).astype("int32")
              for _ in range(n_prefixes)]
    # zipf over the corpus: rank-1 prefix dominates, exactly the shared
    # system-prompt shape (a normalized 1/rank^a draw IS the bounded
    # zipf — np.random.zipf's unbounded tail would need clipping)
    zipf_a = 1.2
    probs = 1.0 / np.arange(1, n_prefixes + 1) ** zipf_a
    probs /= probs.sum()
    choices = rng.choice(n_prefixes, size=n_requests, p=probs)
    prompts = [np.concatenate([corpus[c],
                               rng.randint(0, cfg["vocab_size"],
                                           (suffix_len,)).astype("int32")])
               for c in choices]
    dims = dict(max_len=max_len, num_layers=cfg["num_layers"],
                num_heads=cfg["num_heads"],
                head_dim=cfg["hidden_size"] // cfg["num_heads"])

    def run_mode(sharing: bool, slo_threshold_s=None):
        slo = None if slo_threshold_s is None else SLOTracker(
            [Objective("ttft_p95", "ttft", 0.95,
                       threshold_s=slo_threshold_s)])
        engine = ServingEngine(model, max_len=max_len, slots=slots,
                               max_queue=2 * n_requests,
                               cache_layout="paged", block_size=block,
                               prefill_chunk_tokens=chunk,
                               prefix_sharing=sharing, slo=slo)
        # warm every executable OUTSIDE the timed region (cold TTFT
        # measures XLA, not the scheduler); a warm prompt OFF the
        # corpus so it can never seed the prefix index
        engine.submit(rng.randint(0, cfg["vocab_size"],
                                  (prefix_len,)).astype("int32"), 2)
        while engine.pump(16):
            pass
        engine.metrics.histogram("serving_inter_token_seconds").reset()
        # the warm request is an admission query that can never hit:
        # zero the cumulative counters so the stamped hit rate covers
        # exactly the measured traffic (decode_sweep does the same)
        engine.reset_prefix_stats()
        t0 = time.perf_counter()
        streams = [engine.submit(p, gen) for p in prompts]
        while engine.pump(16):
            pass
        wall = time.perf_counter() - t0
        statuses = [s.result(timeout_s=0) for s in streams]
        return engine, statuses, wall

    def leg(engine, statuses, wall):
        ttfts = [st.ttft_s for st in statuses]
        stats = engine.cache_stats()
        pstats = engine.prefix_stats()
        itl = engine.metrics.histogram("serving_inter_token_seconds")
        out = {
            "cache_layout": stats["cache_layout"],
            "cache_dtype": stats["cache_dtype"],
            "kv_resident_bytes": stats["pool_bytes"],
            "requests": len(statuses),
            "prefix_hit_rate": round(pstats["hit_rate"], 4),
            "prefix_hits": pstats["hits"],
            "prefix_tokens_matched": pstats["tokens_matched"],
            # prefill work + resident HBM the matched blocks were worth
            "prefix_blocks_saved_bytes": kv_reachable_bytes(
                [block] * pstats["blocks_matched"], layout="paged",
                block_size=block, dtype=stats["cache_dtype"], **dims),
            "prefill_chunks": pstats["prefill_chunks_total"],
            "ttft_p50_s": round(float(np.percentile(ttfts, 50)), 5),
            "ttft_p95_s": round(float(np.percentile(ttfts, 95)), 5),
            "itl_p50_s": _histogram_quantile(itl, 0.5),
            "itl_p95_s": _histogram_quantile(itl, 0.95),
            "tokens_per_sec": round(
                sum(st.new_tokens for st in statuses) / wall, 1),
            "wall_s": round(wall, 4),
        }
        if engine.slo is not None:
            obj = engine.slo.snapshot()["objectives"][0]
            out["slo_ttft_burn_fast"] = round(obj["fast_burn_rate"], 4)
            out["slo_ttft_burn_slow"] = round(obj["slow_burn_rate"], 4)
            out["slo_ttft_bad_fraction"] = round(
                obj["total_bad"] / max(1, obj["total_bad"]
                                       + obj["total_good"]), 4)
        return out

    # calibration probe: the sharing-off p50 becomes the TTFT promise
    # both modes are then measured against — a threshold neither mode
    # trivially meets nor trivially misses
    engine, statuses, _ = run_mode(sharing=False)
    threshold = max(1e-4, float(np.percentile(
        [st.ttft_s for st in statuses], 50)))
    engine, statuses, wall = run_mode(sharing=False,
                                      slo_threshold_s=threshold)
    off = leg(engine, statuses, wall)
    engine, statuses, wall = run_mode(sharing=True,
                                      slo_threshold_s=threshold)
    on = leg(engine, statuses, wall)
    out = {
        "prefix_len": prefix_len,
        "suffix_len": suffix_len,
        "generated": gen,
        "slots": slots,
        "block_size": block,
        "prefill_chunk_tokens": chunk,
        "n_prefixes": n_prefixes,
        "zipf_a": zipf_a,
        "slo_ttft_threshold_s": round(threshold, 5),
        "input_staged": False,
        "transfer_note": (
            "prompt upload rides inside the (chunked) prefill term "
            "exactly as in the serving leg; sharing on and off carry "
            "identical traffic and transfer, so their TTFT difference "
            "is pure scheduler+cache behavior"),
        "sharing_on": on,
        "sharing_off": off,
        "prefix_hit_rate": on["prefix_hit_rate"],
        "ttft_p95_improvement_pct": round(
            (off["ttft_p95_s"] - on["ttft_p95_s"])
            / max(1e-9, off["ttft_p95_s"]) * 100.0, 2),
    }
    return out


def bench_serving_overload(pt, jax, on_tpu: bool):
    """L7 traffic-grade-scheduling leg: IDENTICAL bursty mixed-priority
    traffic through the paged engine with the degradation ladder ON vs
    OFF — the closed-loop proof that when both TTFT burn windows fire,
    degrading (preempt low-priority → reduce spec-K → tighten
    admission) beats alerting-and-doing-nothing on the traffic that
    matters:

    - ON/OFF arrival phases: low-priority bursts that saturate slots
      and queue, with high-priority requests landing mid-burst — the
      overload shape §5j exists for;
    - stamps p50/p95/p99 TTFT PER PRIORITY CLASS for both modes, the
      preemption/resume/spill-bytes/tightened-shed counts (what the
      ladder actually did), and the ttft objective's max slow-window
      burn per mode (the SLO plane's own view of the incident);
    - headline: ``ttft_p99_high_improvement_pct`` — high-priority p99
      TTFT must be STRICTLY better with degradation on (acceptance
      contract), and ``slo_burn_drop`` — the burn the ladder bought
      back on the same traffic.

    ``_leg_promotable`` refuses a serving_overload leg whose degraded
    sub-leg cannot say what the ladder did (no preemption stamp) or
    whose sub-legs lack the burn stamp — a closed-loop claim without
    the loop's own evidence measures nothing."""
    from paddle_tpu.models import TransformerLM, gpt_1p3b_config
    from paddle_tpu.serving import AdmissionTightenedError, ServingEngine
    from paddle_tpu.serving.slo import Objective, SLOTracker

    cfg = gpt_1p3b_config()
    if on_tpu:
        cfg.update(num_layers=6)
        prompt_len, gen_low, gen_high = 128, 48, 16
        slots, block = 4, 32
        bursts, burst_size = 3, 6
    else:
        _cpu_smoke_shrink(cfg, max_position=1024)
        prompt_len, gen_low, gen_high = 12, 16, 4
        slots, block = 2, 8
        bursts, burst_size = 4, 4
    max_len = prompt_len + max(gen_low, gen_high)
    # spill-tier HBM headroom: parked victims keep their device copies
    # so resume stays the zero-copy re-map fast path — the leg prices
    # the SCHEDULER, not reclaim-upload churn (which tier-1 pins)
    num_blocks = 1 + (slots + 2) * (-(-max_len // block))
    pt.seed(0)
    model = TransformerLM(**cfg, dropout=0.0)
    rng = np.random.RandomState(0)

    # deterministic arrival plan, shared verbatim by both modes:
    # (tick, rid, prompt, budget, priority) — ON phases flood
    # low-priority work deep enough that the queue's wait TTFTs light
    # the burn alert, then one high-priority request lands MID-DRAIN,
    # while every slot is busy and the alert is already active: the
    # exact moment preempting is the only move that helps
    plan = []
    tick = 0
    for phase in range(bursts):
        for t in range(burst_size):
            plan.append((tick + t, "low-%d-%d" % (phase, t),
                         rng.randint(0, cfg["vocab_size"],
                                     (prompt_len,)).astype("int32"),
                         gen_low, -1))
        plan.append((tick + gen_low + 6, "high-%d" % phase,
                     rng.randint(0, cfg["vocab_size"],
                                 (prompt_len,)).astype("int32"),
                     gen_high, 1))
        # OFF gap: the burst fully drains before the next phase
        tick += burst_size + 3 * gen_low + 8

    def run_mode(degrade: bool, threshold_s: float):
        slo = SLOTracker([Objective("ttft_p95", "ttft", 0.95,
                                    threshold_s=threshold_s)],
                         fast_window=3, slow_window=12)
        engine = ServingEngine(model, max_len=max_len, slots=slots,
                               buckets=[prompt_len, max_len],
                               max_queue=8 * slots,
                               cache_layout="paged", block_size=block,
                               num_blocks=num_blocks,
                               slo=slo, degrade=degrade,
                               degrade_dwell_ticks=1,
                               degrade_clear_ticks=3)
        # warm every executable OUTSIDE the timed region (a cold
        # compile would be the whole TTFT story) — including the spill
        # tier's eager gather/scatter buckets: two warm preempt/resume
        # cycles at different committed lengths cover the pow2 index
        # buckets the timed victims will hit
        warm = engine.submit(rng.randint(0, cfg["vocab_size"],
                                         (prompt_len,)).astype("int32"),
                             gen_low, request_id="warm")
        engine.pump(2)
        engine.preempt("warm")
        engine.pump(6)
        engine.preempt("warm")
        while engine.pump(8):
            pass
        assert warm.result(timeout_s=0).state == "DONE"
        engine.metrics.histogram("serving_inter_token_seconds").reset()
        engine.metrics.counter("serving_preemptions_total").value = 0.0
        engine.metrics.counter("serving_resumes_total").value = 0.0
        engine.metrics.counter("serving_spill_bytes_total").value = 0.0
        streams, shed = {}, []
        max_burn, burn_sum, burn_n = 0.0, 0.0, 0
        horizon = max(t for t, *_ in plan)
        t0 = time.perf_counter()
        step, work = 0, True
        while work or step <= horizon:
            for (t, rid, prompt, budget, prio) in plan:
                if t == step:
                    try:
                        streams[rid] = engine.submit(
                            prompt, budget, request_id=rid,
                            priority=prio)
                    except AdmissionTightenedError:
                        # the ladder shed it — degraded behavior, and
                        # exactly what gets counted, not hidden
                        shed.append(rid)
            work = engine.pump(1)
            obj = engine.slo.snapshot()["objectives"][0]
            max_burn = max(max_burn, obj["slow_burn_rate"])
            burn_sum += obj["slow_burn_rate"]
            burn_n += 1
            step += 1
            if step > 5000:
                raise RuntimeError("overload leg failed to drain")
        wall = time.perf_counter() - t0
        return engine, streams, shed, (max_burn, burn_sum / burn_n), wall

    def leg(engine, streams, shed, burns, wall):
        max_burn, mean_burn = burns
        stats = engine.cache_stats()
        spill = engine.spill_stats()
        snap = engine.metrics.snapshot()
        by_class = {"high": [], "low": []}
        for rid, s in streams.items():
            st = s.result(timeout_s=0)
            if st.state == "DONE" and st.ttft_s is not None:
                by_class["high" if rid.startswith("high")
                         else "low"].append(st.ttft_s)
        out = {
            "cache_layout": stats["cache_layout"],
            "cache_dtype": stats["cache_dtype"],
            "requests": len(streams),
            "requests_shed_tightened": len(shed),
            "preemptions": int(snap["serving_preemptions_total"]),
            "resumes": int(snap["serving_resumes_total"]),
            "spill_bytes_total": int(snap["serving_spill_bytes_total"]),
            "spill_reclaims": spill["reclaims_total"],
            "degrade_transitions":
                engine.slo_snapshot()["degradation"]["transitions"],
            "slo_ttft_burn_slow_max": round(max_burn, 4),
            "slo_ttft_burn_slow_mean": round(mean_burn, 4),
            "wall_s": round(wall, 4),
        }
        for klass, ttfts in by_class.items():
            if ttfts:
                for q in (50, 95, 99):
                    out["ttft_p%d_%s_s" % (q, klass)] = round(
                        float(np.percentile(ttfts, q)), 5)
        return out

    # calibration probe: the ladder-off p25 TTFT becomes the promise —
    # burst-time first tokens (queue waits) violate it, calm ones keep
    # it, so the alert fires exactly during the overload it should
    engine, streams, _, _, _ = run_mode(False, threshold_s=1.0)
    ttfts = [s.result(timeout_s=0).ttft_s for s in streams.values()
             if s.result(timeout_s=0).ttft_s is not None]
    threshold = max(1e-4, float(np.percentile(ttfts, 25)))
    off = leg(*run_mode(False, threshold))
    on = leg(*run_mode(True, threshold))
    out = {
        "prompt_len": prompt_len,
        "gen_low": gen_low,
        "gen_high": gen_high,
        "slots": slots,
        "block_size": block,
        "bursts": bursts,
        "burst_size": burst_size,
        "slo_ttft_threshold_s": round(threshold, 5),
        "input_staged": False,
        "transfer_note": (
            "degradation on and off carry identical traffic and "
            "transfer; their per-class TTFT difference is pure "
            "scheduler behavior (preempt/spill/tighten), which is the "
            "quantity this leg prices"),
        "degrade_on": on,
        "degrade_off": off,
        "ttft_p99_high_improvement_pct": round(
            (off.get("ttft_p99_high_s", 0.0)
             - on.get("ttft_p99_high_s", 0.0))
            / max(1e-9, off.get("ttft_p99_high_s", 0.0)) * 100.0, 2),
        # the burn the ladder bought back: the MEAN slow-window burn
        # over the run (the max saturates identically in both modes
        # the moment any burst violates the promise — it is stamped
        # per mode above, but the mean is the comparable quantity)
        "slo_burn_drop": round(
            off["slo_ttft_burn_slow_mean"]
            - on["slo_ttft_burn_slow_mean"], 4),
    }
    return out


def bench_speculative(pt, jax, on_tpu: bool):
    """L7 speculative-decoding leg: the draft/verify pool
    (``inference.SpeculativePool``) against the PLAIN decode pool at
    matched batch — tokens/s, the acceptance-rate stamp, and the
    draft/verify wall-time split, so the speculative claim is measured,
    never asserted.  Two draft sub-legs bracket the mechanism:

    - ``selfdraft`` (draft IS the target): acceptance ~1.0 by
      construction — the machinery's CEILING, what the round overhead
      costs when every guess lands;
    - ``smalldraft`` (same geometry shrunk, independently initialized):
      the structural configuration a deployment runs; with random
      weights its acceptance is ~chance, making the stamped rate the
      honest explanation of whichever tokens/s it gets (draft QUALITY,
      not machinery, is the whole game — greedy output is
      token-identical to the plain pool in every case, pinned by
      tests/test_speculative.py).

    Every sub-leg carries cache_layout/cache_dtype like the decode leg
    plus ``acceptance_rate``; _leg_promotable rejects speculative legs
    missing the acceptance stamp."""
    from paddle_tpu.inference import GenerationPool, SpeculativePool
    from paddle_tpu.models import TransformerLM, gpt_1p3b_config

    prefill, gen, spec_k = (512, 64, 4) if on_tpu else (32, 16, 4)
    slots = 8 if on_tpu else 4
    cfg = gpt_1p3b_config()
    if on_tpu:
        cfg.update(num_layers=6)  # the one-chip GPT geometry
        draft_cfg = dict(cfg, num_layers=2)
    else:
        _cpu_smoke_shrink(cfg, max_position=1024)
        draft_cfg = dict(cfg, num_layers=1, hidden_size=64,
                         intermediate_size=256)
    pt.seed(0)
    target = TransformerLM(**cfg, dropout=0.0)
    pt.seed(1)
    draft_small = TransformerLM(**draft_cfg, dropout=0.0)
    max_len = prefill + gen
    rng = np.random.RandomState(0)
    prompts = [rng.randint(0, cfg["vocab_size"],
                           (prefill,)).astype("int32")
               for _ in range(slots)]

    def timed_run(pool):
        pool.generate([prompts[0]], 2)  # compile + warm every program
        if hasattr(pool, "reset_acceptance_stats"):
            # the stamped rate must cover exactly the timed region
            pool.reset_acceptance_stats()
        t0 = time.perf_counter()
        outs = pool.generate(prompts, gen)
        wall = time.perf_counter() - t0
        return sum(len(o) for o in outs) / wall, wall

    out = {
        "prefill": prefill,
        "generated": gen,
        "spec_k": spec_k,
        "slots": slots,
        "input_staged": False,
        "transfer_note": (
            "prompt upload rides inside the prefill term exactly as in "
            "the decode leg; per-round host traffic is the emitted "
            "token block plus per-slot acceptance counts — the "
            "scheduler cost this leg compares against plain decoding"),
    }
    plain = GenerationPool(target, max_len, slots=slots,
                           buckets=[prefill])
    plain_tps, plain_wall = timed_run(plain)
    plain_cost = plain.cost_report().get("derived") or {}
    plain_bpt = plain_cost.get("bytes_per_token")
    out["plain_batch%d" % slots] = {
        "cache_layout": "dense", "cache_dtype": "float32",
        "decode_route": "auto",
        "tokens_per_sec": round(plain_tps, 1),
        "wall_s": round(plain_wall, 4),
        "cost_flops_per_token": plain_cost.get("flops_per_token"),
        "cost_bytes_per_token": plain_bpt,
        "bandwidth_util_bytes_per_sec": (
            None if plain_bpt is None
            else round(plain_tps * plain_bpt, 1)),
    }
    # only plain_tps is needed past this point: drop the plain pool's
    # slots x max_len KV cache before building the speculative pools
    # (which each add a draft cache on top of the target's), so the
    # timed sub-legs never carry a dead pool's HBM
    del plain
    best_spec = 0.0
    for tag, draft in (("selfdraft", target),
                       ("smalldraft", draft_small)):
        pool = SpeculativePool(target, draft, max_len, spec_k=spec_k,
                               slots=slots, buckets=[prefill],
                               time_split=True)
        tps, wall = timed_run(pool)
        st = pool.acceptance_stats()  # timed region only (post-reset)
        spec_cost = pool.cost_report().get("derived") or {}
        spec_bpt = spec_cost.get("bytes_per_token")
        sub = {
            "cache_layout": "dense", "cache_dtype": "float32",
            "decode_route": "auto",
            "tokens_per_sec": round(tps, 1),
            "wall_s": round(wall, 4),
            # compiler-reported round cost at the MEASURED acceptance
            # rate (the derivation's basis field says so) — the cost
            # model the speedup_vs_plain stamp can be checked against
            "cost_flops_per_token": spec_cost.get("flops_per_token"),
            "cost_bytes_per_token": spec_bpt,
            "bandwidth_util_bytes_per_sec": (
                None if spec_bpt is None else round(tps * spec_bpt, 1)),
            "speedup_vs_plain": round(tps / plain_tps, 4),
            "acceptance_rate": round(st["acceptance_rate"], 4),
            "rounds": st["rounds"],
            "draft_layers": (draft_cfg["num_layers"]
                             if tag == "smalldraft"
                             else cfg["num_layers"]),
            # the draft/target step-time split: where the round's wall
            # time actually goes (drafting vs the one verify chunk)
            "draft_time_s": round(st["draft_time_s"], 4),
            "verify_time_s": round(st["verify_time_s"], 4),
        }
        out["%s_batch%d" % (tag, slots)] = sub
        best_spec = max(best_spec, tps)
        del pool  # the next sub-leg builds its own target+draft caches
    # the headline is the best SPECULATIVE sub-leg, never the plain
    # baseline: a leg named "speculative" whose headline could fall
    # back to plain_tps would hide a speculative regression from every
    # cross-run comparison (the plain number lives in its own sub-leg)
    out["tokens_per_sec"] = round(best_spec, 1)
    return out


def force_host_devices(env, n: int = 8):
    """Append ``--xla_force_host_platform_device_count=n`` to the
    XLA_FLAGS of ``env`` (any mapping) unless already forced — the
    knob every CPU mesh entry point needs, and one that must land
    before jax initializes its backends.  Shared by the sharded bench
    child and ``tools/decode_sweep.py --mesh``."""
    flags = env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=%d" % n
        ).strip()
    return env


def bench_serving_sharded(pt, jax, on_tpu: bool):
    """GSPMD sharded-serving leg (docs/DESIGN.md §5k): the decode pool
    over dp/mp/dp×mp meshes vs the unsharded pool on IDENTICAL
    traffic, with per-shard compiler-reported cost stamps and a
    measured-vs-ideal ``scaling_efficiency`` column (tok/s ÷
    (baseline tok/s × devices)).

    Runs in a SUBPROCESS: the meshes need multiple devices, and on CPU
    that means ``--xla_force_host_platform_device_count=8`` in
    XLA_FLAGS — which must be set before jax initializes, impossible
    in this already-initialized process.  On an accelerator the child
    inherits the real device set and sweeps whatever meshes fit.

    CPU smoke honesty: 8 virtual devices share one physical CPU, so
    scaling_efficiency well under 1.0 is the EXPECTED reading there —
    the column exists so the on-chip run has a stamped ideal-linear
    comparison, and ``_leg_promotable`` rejects sharded legs whose
    mesh sub-legs lack it (or the per-shard cost stamps)."""
    import subprocess
    import sys

    env = dict(os.environ, _BENCH_SHARDED_CHILD="1")
    env.pop("_BENCH_CHILD", None)
    if not on_tpu:
        force_host_devices(env)
        env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__)], env=env,
        capture_output=True, text=True,
        timeout=float(os.environ.get("BENCH_SHARDED_TIMEOUT_S", "900")))
    if proc.returncode != 0:
        raise RuntimeError("sharded bench child failed (rc %d): %s"
                           % (proc.returncode, proc.stderr[-500:]))
    lines = [ln for ln in proc.stdout.strip().splitlines()
             if ln.strip().startswith("{")]
    if not lines:
        raise RuntimeError("sharded bench child printed no JSON record: "
                           "%s" % proc.stdout[-500:])
    return json.loads(lines[-1])


def _sharded_bench_child():
    """Child half of ``bench_serving_sharded``: measures under its own
    jax runtime (forced multi-device on CPU) and prints ONE JSON line.
    Every mesh sub-leg stamps cache provenance, per-shard cost
    (``cost_*_per_shard`` — the compiler's analyses of the partitioned
    per-device module, via the same jit.aot path every pool
    executable compiles through), per-shard HBM from the allocator,
    and scaling_efficiency vs the in-run unsharded baseline."""
    import jax

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")

    import paddle_tpu as pt
    from paddle_tpu.inference import GenerationPool
    from paddle_tpu.jit.mesh import DecodeMesh
    from paddle_tpu.models import TransformerLM, gpt_1p3b_config

    on_tpu = jax.default_backend() not in ("cpu",)
    prefill, gen = (512, 64) if on_tpu else (32, 16)
    cfg = gpt_1p3b_config()
    if on_tpu:
        cfg.update(num_layers=6)  # the one-chip GPT geometry
    else:
        _cpu_smoke_shrink(cfg, max_position=1024)
    rng = np.random.RandomState(0)
    max_len = prefill + gen
    slots = 8
    n_dev = len(jax.devices())
    out = {
        "prefill": prefill,
        "generated": gen,
        "slots": slots,
        "devices_available": n_dev,
        "input_staged": False,
        "transfer_note": (
            "prompt upload rides inside the prefill term exactly as in "
            "the serving leg; the timed region is the same "
            "submit+drain loop per mesh, so cross-mesh ratios (the "
            "scaling_efficiency column) carry no transfer bias"),
    }
    base_tps = None
    best = 0.0
    # the _qint8 sub-legs re-run the mp>1 meshes with the decode-step
    # mp all-reduces replaced by the block-int8 two-stage collectives
    # (docs §5r) on IDENTICAL traffic; every mp>1 leg stamps its
    # traced-shape collective_bytes_per_token so quantized-vs-dense is
    # a stamped comparison, never a vibe
    for dp, mp, cq in ((1, 1, "none"), (2, 1, "none"), (1, 2, "none"),
                       (2, 2, "none"), (1, 2, "int8"), (2, 2, "int8")):
        if dp * mp > n_dev or cfg["num_heads"] % mp or slots % dp:
            continue
        pt.seed(0)
        model = TransformerLM(**cfg, dropout=0.0)
        mesh = None if dp == mp == 1 \
            else DecodeMesh(dp, mp, collective_quant=cq)
        pool = GenerationPool(model, max_len, slots=slots,
                              buckets=[prefill], cache_layout="paged",
                              block_size=16, mesh=mesh)
        prompts = [rng.randint(0, cfg["vocab_size"],
                               (prefill,)).astype("int32")
                   for _ in range(2 * slots)]
        pool.generate(prompts[:1], 2)  # compile + warm
        walls = []
        toks = 0
        for _ in range(2):  # min-of-2, same noise discipline as serving
            t0 = time.perf_counter()
            outs = pool.generate(prompts, gen)
            walls.append(time.perf_counter() - t0)
            toks = sum(len(o) for o in outs)
        tps = toks / min(walls)
        stats = pool.cache_stats()
        cost = pool.cost_report().get("derived") or {}
        name = "mesh_%dx%d" % (dp, mp)
        if cq != "none":
            name += "_q%s" % cq
        if mesh is None:
            base_tps = tps
            scaling = None
        else:
            scaling = tps / (base_tps * dp * mp) if base_tps else None
        leg = {
            "mesh_dp": dp,
            "mesh_mp": mp,
            "devices": dp * mp,
            "cache_layout": stats["cache_layout"],
            "cache_dtype": stats["cache_dtype"],
            "kv_resident_bytes": stats["pool_bytes"],
            "kv_resident_bytes_per_shard":
                stats["per_shard"][0]["pool_bytes"],
            "cost_flops_per_shard": cost.get("step_flops"),
            "cost_bytes_per_shard": cost.get("step_bytes_accessed"),
            "cost_hbm_reserved_per_shard": cost.get("hbm_reserved_bytes"),
            "cost_basis": cost.get("basis"),
            "tokens_per_sec": round(tps, 1),
            "wall_s": round(min(walls), 4),
        }
        if scaling is not None:
            leg["scaling_efficiency"] = round(scaling, 4)
        if mesh is not None:
            leg["collective_quant"] = cq
            # present whenever the decode step has mp-axis collectives
            # (mp>1): traced-shape wire bytes per committed token, the
            # quantized figure beside the dense ring equivalent
            if "collective_bytes_per_token" in cost:
                leg["collective_bytes_per_token"] = \
                    cost["collective_bytes_per_token"]
                leg["collective_dense_bytes_per_token"] = \
                    cost["collective_dense_bytes_per_token"]
        out[name] = leg
        best = max(best, tps)
    out["tokens_per_sec"] = round(best, 1)
    print(json.dumps(_round_tree(out)))


def bench_serving_disagg(pt, jax, on_tpu: bool):
    """L7 disaggregated-serving leg (docs/DESIGN.md §5n): the SAME
    zipf-mixed traffic — mostly short interactive prompts, a heavy
    tail of long prefill jobs, the shape whose chunked prefills the
    fused engine interleaves into resident decodes — through the fused
    engine vs the prefill/decode pair behind ``DisaggregatedServing``.

    Stamps the headline the tier split claims and the hand-off's own
    cost, so neither can silently decay:

    - ``ttft_p95_improvement_pct`` / ``itl_p95_improvement_pct``:
      disagg vs fused on identical traffic (front-observed, so the
      disagg numbers INCLUDE the hand-off wait — the honest end-to-end
      reading; on CPU smoke both tiers timeshare one core, so ~0 or
      negative is the expected reading there — the columns exist so
      the on-chip run has a stamped comparison);
    - ``kv_transfers`` / ``kv_transfer_bytes``: every request must
      actually cross the contract (``_leg_promotable`` rejects a
      disagg record whose hand-off never fired — it measured two idle
      engines), and the bytes are the wire cost of the split;
    - ``handoff_wait_p95_s``: the export-to-adopt latency the front's
      deadline estimate folds in;
    - ``tokens_lost``: disagg greedy output vs the fused reference.
      MUST be 0 — a hand-off can never change tokens, only where they
      are computed, and the gate structurally refuses a lossy leg."""
    import shutil
    import tempfile

    from paddle_tpu.models import TransformerLM, gpt_1p3b_config
    from paddle_tpu.serving import DisaggregatedServing, ServingEngine

    cfg = gpt_1p3b_config()
    if on_tpu:
        cfg.update(num_layers=6)
        short_len, long_len, gen = 32, 384, 24
        chunk, block, slots, n_requests = 64, 32, 4, 16
    else:
        _cpu_smoke_shrink(cfg, max_position=1024)
        short_len, long_len, gen = 8, 48, 6
        chunk, block, slots, n_requests = 16, 8, 2, 8
    max_len = long_len + gen
    pt.seed(0)
    model = TransformerLM(**cfg, dropout=0.0)
    rng = np.random.RandomState(0)
    # zipf over prompt-length ranks: rank 1 is the short interactive
    # prompt (dominates), the tail ranks are the long prefill-heavy
    # jobs (same normalized 1/rank^a draw as the prefix leg)
    zipf_a = 1.1
    ranks = np.linspace(short_len, long_len, 4).astype(int)
    probs = 1.0 / np.arange(1, len(ranks) + 1) ** zipf_a
    probs /= probs.sum()
    choices = rng.choice(len(ranks), size=n_requests, p=probs)
    prompts = [rng.randint(0, cfg["vocab_size"],
                           (int(ranks[c]),)).astype("int32")
               for c in choices]
    shared = dict(cache_layout="paged", block_size=block,
                  buckets=[max_len], temperature=0.0)
    workdir = tempfile.mkdtemp(prefix="bench-disagg-")

    def measure(target, itl_hist, after_warm=None):
        # warm every executable on BOTH sides of the hand-off outside
        # the timed region (a long warm prompt crosses the transfer on
        # the disagg target), then measure the zipf burst
        target.submit(rng.randint(0, cfg["vocab_size"],
                                  (long_len,)).astype("int32"), 2)
        while target.pump(8):
            pass
        itl_hist.reset()
        if after_warm is not None:
            after_warm()
        t0 = time.perf_counter()
        streams = [target.submit(p, gen, request_id="r%d" % i)
                   for i, p in enumerate(prompts)]
        while target.pump(4):
            pass
        wall = time.perf_counter() - t0
        return [s.result(timeout_s=0) for s in streams], wall

    def leg(statuses, wall, itl_hist, stats):
        ttfts = [st.ttft_s for st in statuses]
        return {
            "cache_layout": stats["cache_layout"],
            "cache_dtype": stats["cache_dtype"],
            "requests": len(statuses),
            "ttft_p50_s": round(float(np.percentile(ttfts, 50)), 5),
            "ttft_p95_s": round(float(np.percentile(ttfts, 95)), 5),
            "itl_p50_s": _histogram_quantile(itl_hist, 0.5),
            "itl_p95_s": _histogram_quantile(itl_hist, 0.95),
            "tokens_per_sec": round(
                sum(st.new_tokens for st in statuses) / wall, 1),
            "wall_s": round(wall, 4),
        }

    try:
        # fused reference: one engine, chunked prefill interleaved with
        # resident decodes — also the greedy byte-identity reference
        engine = ServingEngine(model, max_len=max_len, slots=2 * slots,
                               max_queue=2 * n_requests,
                               prefill_chunk_tokens=chunk, **shared)
        itl = engine.metrics.histogram("serving_inter_token_seconds")
        statuses, wall = measure(engine, itl)
        fused = leg(statuses, wall, itl, engine.cache_stats())
        want = {st.request_id: np.asarray(st.tokens) for st in statuses}
        engine.shutdown()

        # disaggregated pair on the same traffic: prefill tier admits
        # and chunks, decode tier adopts over the transfer contract;
        # TTFT/ITL come from the FRONT's registry (end-to-end, the
        # hand-off wait included)
        front = DisaggregatedServing(
            model, max_len, transfer_dir=os.path.join(workdir, "xfer"),
            prefill_chunk_tokens=chunk, prefill_slots=slots,
            decode_slots=slots, max_queue=2 * n_requests, **shared)
        itl = front.metrics.histogram("serving_inter_token_seconds")
        base = {}

        def snap_after_warm():
            # the warm request crosses the transfer too: snapshot the
            # counters at the timed region's edge so the stamped
            # transfer count/bytes cover exactly the measured traffic
            base["xfers"] = front._c_transfers.value
            base["bytes"] = front._c_transfer_bytes.value
            front.metrics.histogram("serving_ttft_seconds").reset()
            front.metrics.histogram("serving_handoff_wait_s").reset()

        statuses, wall = measure(front, itl,
                                 after_warm=snap_after_warm)
        dleg = leg(statuses, wall, itl, front.decode.cache_stats())
        tokens_lost = 0
        for st in statuses:
            ref = want[st.request_id]
            got = np.asarray(st.tokens)
            tokens_lost += max(0, len(ref) - len(got)) + int(
                (got[:len(ref)] != ref[:len(got)]).sum())
        dleg.update({
            "kv_transfers": int(front._c_transfers.value
                                - base["xfers"]),
            "kv_transfer_bytes": int(front._c_transfer_bytes.value
                                     - base["bytes"]),
            "handoffs_degraded": int(front._c_degraded.value),
            "handoff_wait_p95_s": _histogram_quantile(
                front.metrics.histogram("serving_handoff_wait_s"),
                0.95),
            "tokens_lost": tokens_lost,
        })
        front.shutdown()

        def imp(key):
            off, on = fused.get(key), dleg.get(key)
            if not isinstance(off, (int, float)) \
                    or not isinstance(on, (int, float)):
                return None
            return round((off - on) / max(1e-9, off) * 100.0, 2)

        return {
            "short_len": short_len,
            "long_len": long_len,
            "generated": gen,
            "slots_per_tier": slots,
            "block_size": block,
            "prefill_chunk_tokens": chunk,
            "zipf_a": zipf_a,
            "input_staged": False,
            "transfer_note": (
                "prompt upload rides inside the (chunked) prefill term "
                "exactly as in the serving leg, identically on both "
                "sub-legs; the K/V hand-off's own wire cost is stamped "
                "explicitly (kv_transfer_bytes, handoff_wait_p95_s) "
                "rather than hidden in the ratio"),
            "fused": fused,
            "disagg": dleg,
            "kv_transfers": dleg["kv_transfers"],
            "kv_transfer_bytes": dleg["kv_transfer_bytes"],
            "tokens_lost": tokens_lost,
            "ttft_p95_improvement_pct": imp("ttft_p95_s"),
            "itl_p95_improvement_pct": imp("itl_p95_s"),
        }
    finally:
        shutil.rmtree(workdir, ignore_errors=True)


def bench_serving_fleet(pt, jax, on_tpu: bool):
    """L7 serving-fleet leg (docs/DESIGN.md §5o): IDENTICAL bursty
    zipf traffic with shared-prefix groups over 1 vs 2 vs 4 engines,
    plus a chaos sub-leg that hard-abandons one engine mid-burst.

    Stamps the three claims the fleet tier makes and their provenance:

    - ``scaling_efficiency``: tokens/s at 4 engines over 4x the
      1-engine rate (and ``scaling_efficiency_2`` for the pair) — the
      data-parallel-replica argument measured, not asserted.  On CPU
      smoke every engine timeshares ONE core, so ~1/N is the expected
      reading there (same caveat as the sharded leg) — the column
      exists so the multi-host run has a stamped comparison;
    - ``prefix_affinity_hit_rate``: the fraction of routed requests
      the affinity hash placed (vs least-loaded fallback) on the
      4-engine sub-leg — a fleet whose router never fires is N
      independent caches wearing a fleet's name;
    - ``migration_rto_s``: hard-abandon of a mid-burst engine to
      every victim decoding again on a survivor — the fleet's
      recovery-time objective, measured at the front;
    - ``tokens_lost``: every sub-leg's greedy output (including the
      chaos one, one engine dead mid-burst) vs the calm 1-engine
      reference.  MUST be 0 — routing and migration move computation,
      never change tokens, and the gate refuses a lossy record."""
    import shutil
    import tempfile

    from paddle_tpu.models import TransformerLM, gpt_1p3b_config
    from paddle_tpu.serving import ServingEngine, ServingFleet

    cfg = gpt_1p3b_config()
    if on_tpu:
        cfg.update(num_layers=6)
        head_len, tail_lo, tail_hi, gen = 64, 16, 96, 24
        chunk, block, slots, n_requests = 64, 32, 4, 24
    else:
        _cpu_smoke_shrink(cfg, max_position=1024)
        head_len, tail_lo, tail_hi, gen = 24, 4, 16, 6
        chunk, block, slots, n_requests = 16, 8, 2, 8
    max_len = head_len + tail_hi + gen
    pt.seed(0)
    model = TransformerLM(**cfg, dropout=0.0)
    rng = np.random.RandomState(0)
    # bursty zipf over PREFIX GROUPS: a few shared heads (system
    # prompts) dominate by the same 1/rank^a draw the prefix leg uses,
    # each request appending its own random tail — the traffic shape
    # affinity routing exists for
    zipf_a = 1.1
    n_groups = 4
    heads = [rng.randint(0, cfg["vocab_size"], (head_len,))
             .astype("int32") for _ in range(n_groups)]
    probs = 1.0 / np.arange(1, n_groups + 1) ** zipf_a
    probs /= probs.sum()
    groups = rng.choice(n_groups, size=n_requests, p=probs)
    prompts = [np.concatenate([
        heads[g], rng.randint(0, cfg["vocab_size"],
                              (int(rng.randint(tail_lo, tail_hi)),))
        .astype("int32")]) for g in groups]
    workdir = tempfile.mkdtemp(prefix="bench-fleet-")

    def make_fleet(engines, tag):
        # each fleet gets its own spill dir: sub-legs reuse request
        # ids, and a stale transfer file from a previous fleet must
        # never be adoptable by the next one
        spill = os.path.join(workdir, "spill-%s" % tag)

        def factory(engine_id, registry):
            return ServingEngine(
                model, max_len=max_len, slots=slots,
                max_queue=2 * n_requests, cache_layout="paged",
                block_size=block, prefill_chunk_tokens=chunk,
                prefix_sharing=True, temperature=0.0,
                spill_tier="disk", spill_dir=spill,
                metrics=registry)

        return ServingFleet(factory, engines=engines)

    def warm(fleet):
        # warm every engine's executables OUTSIDE the timed region by
        # submitting directly to each (the router would happily pile
        # warm traffic on one engine and leave another to compile
        # inside the measurement)
        for eng in fleet.engines().values():
            eng.submit(rng.randint(0, cfg["vocab_size"],
                                   (head_len + tail_hi,))
                       .astype("int32"), 2)
        while any(e.live_requests or e.queue_depth
                  for e in fleet.engines().values()):
            fleet.pump(1)

    def measure(fleet):
        warm(fleet)
        itl = fleet.metrics.histogram("serving_inter_token_seconds")
        itl.reset()
        fleet.metrics.histogram("serving_ttft_seconds").reset()
        routed0 = {k: c.value for k, c in fleet._routed.items()}
        t0 = time.perf_counter()
        streams = []
        for i, p in enumerate(prompts):
            # bursty-but-ordered arrivals: a tick between submits
            # lets a later request find an earlier one's shared head
            # RESIDENT — the condition affinity routing exists for
            # (greedy output is arrival-order independent, so the
            # byte-identity reference is unaffected)
            streams.append(fleet.submit(p, gen, request_id="r%d" % i))
            fleet.pump(1)
        while fleet.pump(4):
            pass
        wall = time.perf_counter() - t0
        routed = {k: c.value - routed0[k]
                  for k, c in fleet._routed.items()}
        return [s.result(timeout_s=0) for s in streams], wall, \
            itl, routed

    def lost_vs(want, statuses):
        lost = 0
        for st in statuses:
            ref, got = want[st.request_id], np.asarray(st.tokens)
            lost += max(0, len(ref) - len(got)) + int(
                (got[:len(ref)] != ref[:len(got)]).sum())
        return lost

    def leg(statuses, wall, itl, routed, stats):
        ttfts = [st.ttft_s for st in statuses]
        total = max(1.0, routed["affinity"] + routed["load"])
        return {
            "cache_layout": stats["cache_layout"],
            "cache_dtype": stats["cache_dtype"],
            "requests": len(statuses),
            "ttft_p50_s": round(float(np.percentile(ttfts, 50)), 5),
            "ttft_p95_s": round(float(np.percentile(ttfts, 95)), 5),
            "itl_p95_s": _histogram_quantile(itl, 0.95),
            "tokens_per_sec": round(
                sum(st.new_tokens for st in statuses) / wall, 1),
            "wall_s": round(wall, 4),
            "routed_affinity": int(routed["affinity"]),
            "routed_load": int(routed["load"]),
            "prefix_affinity_hit_rate": round(
                routed["affinity"] / total, 3),
        }

    try:
        subs = {}
        want = None
        tokens_lost = 0
        for n_engines in (1, 2, 4):
            fleet = make_fleet(n_engines, "n%d" % n_engines)
            statuses, wall, itl, routed = measure(fleet)
            sub = leg(statuses, wall, itl, routed,
                      fleet.engines()["e0"].cache_stats())
            if want is None:
                # the calm 1-engine run is the byte-identity reference
                # for every other sub-leg, chaos included
                want = {st.request_id: np.asarray(st.tokens)
                        for st in statuses}
            else:
                sub["tokens_lost"] = lost_vs(want, statuses)
                tokens_lost += sub["tokens_lost"]
                sub["scaling_efficiency"] = round(
                    sub["tokens_per_sec"]
                    / (n_engines * subs["engines_1"]["tokens_per_sec"]),
                    3)
            subs["engines_%d" % n_engines] = sub
            fleet.shutdown(drain=False)

        # chaos sub-leg: same traffic over 2 engines, one hard-
        # abandoned mid-burst; the RTO clock runs from the abandon
        # call until EVERY migrated victim has produced a fresh token
        # on (or finished on) a survivor
        fleet = make_fleet(2, "chaos")
        warm(fleet)
        t0 = time.perf_counter()
        streams = [fleet.submit(p, gen, request_id="r%d" % i)
                   for i, p in enumerate(prompts)]
        fleet.pump(2)
        victim_eid = next(iter(
            r.engine_id for r in fleet._records.values()))
        pre = {r.rid: len(r.tokens)
               for r in fleet._records.values()
               if r.engine_id == victim_eid}
        t_kill = time.perf_counter()
        migrated = fleet.hard_abandon(victim_eid, error="bench-chaos")
        while any(rid in fleet._records
                  and len(fleet._records[rid].tokens) <= pre[rid]
                  for rid in migrated):
            fleet.pump(1)
        rto = time.perf_counter() - t_kill
        while fleet.pump(4):
            pass
        wall = time.perf_counter() - t0
        statuses = [s.result(timeout_s=0) for s in streams]
        chaos_lost = lost_vs(want, statuses)
        tokens_lost += chaos_lost
        stats = fleet.engines()["e1" if victim_eid == "e0"
                                else "e0"].cache_stats()
        subs["chaos"] = {
            "cache_layout": stats["cache_layout"],
            "cache_dtype": stats["cache_dtype"],
            "requests": len(statuses),
            "tokens_per_sec": round(
                sum(st.new_tokens for st in statuses) / wall, 1),
            "wall_s": round(wall, 4),
            "engine_killed": victim_eid,
            "requests_migrated": len(migrated),
            "migration_rto_s": round(rto, 5),
            "tokens_lost": chaos_lost,
            "byte_identical": chaos_lost == 0,
        }
        fleet.shutdown(drain=False)

        return dict(subs, **{
            "head_len": head_len,
            "generated": gen,
            "slots_per_engine": slots,
            "block_size": block,
            "prefill_chunk_tokens": chunk,
            "zipf_a": zipf_a,
            "prefix_groups": n_groups,
            "input_staged": False,
            "transfer_note": (
                "prompt upload rides inside the (chunked) prefill "
                "term identically on every sub-leg; the fleet adds no "
                "device transfer of its own (routing and migration "
                "bookkeeping are host-side), and the migrated K/V "
                "file cost is inside migration_rto_s"),
            "scaling_efficiency": subs["engines_4"][
                "scaling_efficiency"],
            "scaling_efficiency_2": subs["engines_2"][
                "scaling_efficiency"],
            "prefix_affinity_hit_rate": subs["engines_4"][
                "prefix_affinity_hit_rate"],
            "migration_rto_s": subs["chaos"]["migration_rto_s"],
            "requests_migrated": subs["chaos"]["requests_migrated"],
            "tokens_lost": tokens_lost,
        })
    finally:
        shutil.rmtree(workdir, ignore_errors=True)


def bench_serving_lora(pt, jax, on_tpu: bool):
    """L7 multi-LoRA leg (docs/DESIGN.md §5q): IDENTICAL greedy traffic
    over 8 fine-tunes served three ways — base-only on one engine
    (``adapters_1``), all 8 adapters MIXED in one engine's batch off the
    stacked bank (``shared_8``), and 8 dedicated one-adapter engines
    (``dedicated_8``, the deployment shape the bank replaces).

    Stamps the three claims the as-data adapter seam makes:

    - ``tokens_per_sec``: mixed-adapter throughput on ONE engine vs the
      aggregate of 8 dedicated engines on the same traffic.  On CPU
      smoke all engines timeshare one core, so the dedicated aggregate
      is sequential-sum wall — the column exists for the on-chip
      comparison;
    - ``weight_hbm_bytes``: resident weight bytes per sub-leg (base +
      bank for the shared engine; 8 full base copies for the dedicated
      fleet) and ``weight_bytes_saved`` — the HBM the bank buys back;
    - ``compiles_during_traffic``: executable-cache growth while the
      mixed-adapter/mixed-nothing traffic runs — MUST be 0 (the
      exactly-two contract: adapter ids and sampling are traced DATA),
      and ``hot_load_compiles`` pins that ``load_adapter`` of a fresh
      fine-tune into the live engine is a device write, not a compile;
      ``cost_version_changed`` must stay False across steady ticks.
    - ``tokens_lost``: shared-bank tokens vs each request's dedicated
      engine — the bank must change WHERE the delta math runs, never
      the tokens (greedy byte-identity, refused by the gate if lossy).
    """
    from paddle_tpu.models import TransformerLM, gpt_1p3b_config
    from paddle_tpu.nn import lora
    from paddle_tpu.serving import ServingEngine

    n_adapters = 8
    cfg = gpt_1p3b_config()
    if on_tpu:
        cfg.update(num_layers=6)
        prefill, gen, slots, rank = 256, 32, 8, 16
    else:
        _cpu_smoke_shrink(cfg, max_position=1024)
        prefill, gen, slots, rank = 24, 6, 4, 4
    max_len = prefill + gen
    rng = np.random.RandomState(0)
    prompts = [rng.randint(0, cfg["vocab_size"], (prefill,))
               .astype("int32") for _ in range(2 * n_adapters)]
    # request i runs fine-tune (i % 8) + 1 — every adapter appears in
    # the mixed batch, and the round-robin keeps the dedicated split
    # balanced
    want_adapter = [(i % n_adapters) + 1 for i in range(len(prompts))]

    def make_model(bank_rows):
        pt.seed(0)  # identical base weights across every sub-leg
        m = TransformerLM(**cfg, dropout=0.0)
        lora.attach_lora(m, n_adapters=bank_rows, rank=rank)
        return m

    def weight_hbm_bytes(model) -> int:
        total = 0
        for p in model.parameters():
            v = getattr(p, "_value", None)
            if v is not None:
                total += int(np.prod(v.shape)) * v.dtype.itemsize
        return total

    def run(engine, idx, adapters):
        """Time requests ``idx`` (adapter per ``adapters``) through a
        warmed engine; returns (statuses, wall, compile/cost deltas)."""
        engine.submit(rng.randint(0, cfg["vocab_size"],
                                  (prefill,)).astype("int32"), 2)
        while engine.pump(8):
            pass
        compiles0 = sum(engine.compile_counts().values())
        cost0 = engine._pool.cost_version()
        t0 = time.perf_counter()
        streams = [engine.submit(prompts[i], gen, adapter=adapters[i],
                                 request_id="r%d" % i) for i in idx]
        while engine.pump(16):
            pass
        wall = time.perf_counter() - t0
        statuses = [s.result(timeout_s=0) for s in streams]
        compiled = sum(engine.compile_counts().values()) - compiles0
        return statuses, wall, compiled, \
            engine._pool.cost_version() != cost0

    def leg(engine, statuses, wall, n_served, compiled, cost_moved):
        stats = engine.cache_stats()
        return {
            "cache_layout": stats["cache_layout"],
            "cache_dtype": stats["cache_dtype"],
            "requests": len(statuses),
            "adapters": n_served,
            "tokens_per_sec": round(
                sum(st.new_tokens for st in statuses) / wall, 1),
            "wall_s": round(wall, 4),
            "compiles_during_traffic": compiled,
            "cost_version_changed": bool(cost_moved),
        }

    out = {
        "adapters": n_adapters,
        "rank": rank,
        "prefill": prefill,
        "generated": gen,
        "slots": slots,
        "input_staged": False,
        "transfer_note": (
            "prompt upload rides inside the prefill term identically "
            "on every sub-leg; adapter weights are loaded OUTSIDE the "
            "timed region (the hot-load stamp times nothing — it "
            "counts compiles), so the timed traffic differs only in "
            "the per-slot adapter ids riding the batch"),
    }
    all_idx = list(range(len(prompts)))

    # -- shared engine: one base copy + the stacked bank -----------------
    model = make_model(n_adapters + 1)
    fresh = {i: lora.random_adapter(model, seed=i)
             for i in range(1, n_adapters + 1)}
    engine = ServingEngine(model, max_len=max_len, slots=slots,
                           buckets=[prefill], max_queue=4 * len(prompts))
    for i in range(1, n_adapters + 1):
        engine.load_adapter(i, fresh[i])
    # base-only traffic through the SAME bank-attached engine: the
    # 1-adapter reading on the one-engine deployment
    statuses, wall, compiled, moved = run(
        engine, all_idx, [0] * len(prompts))
    out["adapters_1"] = dict(
        leg(engine, statuses, wall, 1, compiled, moved),
        weight_hbm_bytes=weight_hbm_bytes(model),
        adapter_bank_bytes=lora.adapter_bank_bytes(model))
    # all 8 fine-tunes mixed in one batch
    statuses, wall, compiled, moved = run(engine, all_idx, want_adapter)
    shared_bytes = weight_hbm_bytes(model)
    out["shared_8"] = dict(
        leg(engine, statuses, wall, n_adapters, compiled, moved),
        weight_hbm_bytes=shared_bytes,
        adapter_bank_bytes=lora.adapter_bank_bytes(model))
    shared_tokens = {st.request_id: np.asarray(st.tokens)
                     for st in statuses}
    # hot-load: overwrite a bank row on the LIVE engine — a device
    # write, never a compile (the refresh_weights-style contract)
    compiles0 = sum(engine.compile_counts().values())
    cost0 = engine._pool.cost_version()
    engine.load_adapter(1, lora.random_adapter(model, seed=101))
    st = engine.submit(prompts[0], 2, adapter=1)
    while engine.pump(8):
        pass
    st.result(timeout_s=0)
    out["hot_load_compiles"] = \
        sum(engine.compile_counts().values()) - compiles0
    out["hot_load_cost_version_changed"] = \
        engine._pool.cost_version() != cost0
    engine.shutdown(drain=False)

    # -- dedicated fleet: 8 engines, one fine-tune each ------------------
    tokens_lost = 0
    ded_bytes = 0
    ded_tokens = 0
    ded_wall = 0.0
    ded_compiled = 0
    ded_moved = False
    for a in range(1, n_adapters + 1):
        m = make_model(2)  # identity row + this engine's one fine-tune
        # the SAME weights the shared bank serves for this fine-tune
        # (random_adapter is keyed by shapes + seed, both identical)
        lora.load_adapter(m, 1, lora.random_adapter(m, seed=a))
        eng = ServingEngine(m, max_len=max_len, slots=slots,
                            buckets=[prefill],
                            max_queue=4 * len(prompts))
        idx = [i for i in all_idx if want_adapter[i] == a]
        statuses, wall, compiled, moved = run(
            eng, idx, {i: 1 for i in idx})
        ded_bytes += weight_hbm_bytes(m)
        ded_tokens += sum(st.new_tokens for st in statuses)
        ded_wall += wall
        ded_compiled += compiled
        ded_moved = ded_moved or moved
        for st in statuses:
            ref = shared_tokens[st.request_id]
            got = np.asarray(st.tokens)
            tokens_lost += max(0, len(ref) - len(got)) + int(
                (got[:len(ref)] != ref[:len(got)]).sum())
        last_stats = eng.cache_stats()
        eng.shutdown(drain=False)
    out["dedicated_8"] = {
        "cache_layout": last_stats["cache_layout"],
        "cache_dtype": last_stats["cache_dtype"],
        "engines": n_adapters,
        "requests": len(prompts),
        "adapters": n_adapters,
        "tokens_per_sec": round(ded_tokens / ded_wall, 1),
        "wall_s": round(ded_wall, 4),
        "compiles_during_traffic": ded_compiled,
        "cost_version_changed": bool(ded_moved),
        "weight_hbm_bytes": ded_bytes,
    }
    out["weight_bytes_saved"] = ded_bytes - shared_bytes
    out["weight_bytes_ratio"] = round(shared_bytes / ded_bytes, 4)
    out["tokens_lost"] = tokens_lost
    out["tokens_per_sec"] = out["shared_8"]["tokens_per_sec"]
    return out


def _probe_accelerator(timeout_s: int = 180) -> bool:
    """Check from a THROWAWAY subprocess that the accelerator runtime
    answers; a wedged tunnel (the axon transport can hang for hours) must
    not hang the bench — we fall back to CPU and still emit the JSON line."""
    import subprocess
    import sys

    try:
        proc = subprocess.run(
            [sys.executable, "-c",
             "import jax; d=jax.devices(); print(d[0].platform)"],
            capture_output=True, text=True, timeout=timeout_s)
        return proc.returncode == 0 and "cpu" not in proc.stdout
    except subprocess.TimeoutExpired:
        return False


def _round_tree(obj):
    if isinstance(obj, float):
        return round(obj, 4)
    if isinstance(obj, dict):
        return {k: _round_tree(v) for k, v in obj.items()}
    if isinstance(obj, list):
        return [_round_tree(v) for v in obj]
    return obj


def _git_rev() -> str:
    import subprocess
    try:
        return subprocess.run(
            ["git", "-C", _REPO, "rev-parse", "--short", "HEAD"],
            capture_output=True, text=True, timeout=10).stdout.strip()
    except Exception:
        return "unknown"


def _acquire_chip_lock(timeout_s: float = 1800.0):
    """Blocking single-flight lock on the one real chip. Returns the open
    fd (held for process lifetime) or None if another bench held it past
    the timeout — in which case the caller measures on CPU rather than
    contending for the accelerator transport."""
    import fcntl
    fd = os.open(_LOCKFILE, os.O_CREAT | os.O_RDWR)
    deadline = time.time() + timeout_s
    while True:
        try:
            fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
            os.ftruncate(fd, 0)
            os.write(fd, str(os.getpid()).encode())
            return fd
        except OSError:
            if time.time() >= deadline:
                os.close(fd)
                return None
            time.sleep(5.0)


def _persist_tpu_record(record: dict) -> None:
    """Write the verified on-chip record atomically and append to history."""
    tmp = _TPU_RECORD + ".tmp"
    with open(tmp, "w") as f:
        json.dump(record, f, indent=2)
        f.write("\n")
    os.replace(tmp, _TPU_RECORD)
    with open(_HISTORY, "a") as f:
        f.write(json.dumps(record) + "\n")


def _load_tpu_record():
    try:
        with open(_TPU_RECORD) as f:
            return json.load(f)
    except Exception:
        return None


def _primary(bert_leg, extra):
    return {
        "metric": "bert_base_tokens_per_sec_per_chip",
        "value": round(bert_leg["tokens_per_sec"], 1),
        "unit": "tokens/s",
        "vs_baseline": round(bert_leg["mfu"] / 0.40, 4),
        "extra": _round_tree(extra),
    }


def _stored_bert():
    """(stored_record, bert_leg, rejected_reason) from the last verified
    on-chip run; handles the legacy record shape.  The bert leg is gated
    by _leg_promotable like any other: a stored headline that cannot
    prove it measured the chip is not promoted — but the reason is
    returned so the fallback output says 'bert leg rejected: <why>'
    rather than pretending no record exists."""
    stored = _load_tpu_record()
    bert = (stored or {}).get("legs", {}).get("bert") or \
        (stored or {}).get("bert")
    reason = None
    if bert is not None:
        ok, why = _leg_promotable("bert", bert)
        if not ok:
            bert, reason = None, why
    return stored, bert, reason


def _leg_promotable(name: str, leg: dict):
    """(ok, reason) gate every stored leg must pass before promotion.

    Round 4 published a resnet leg that timed the axon tunnel (77 MB/step
    of host->device transfer) instead of the chip; this gate makes that
    class of number structurally unpromotable: a leg must either have been
    timed with device-staged inputs (``input_staged``) or carry an explicit
    ``transfer_note`` showing the transfer bias is negligible, and resnet
    legs must be stamped with the current MFU convention (pre-fix records
    understate MFU exactly 2x — see RESNET50_FWD_FLOPS)."""
    if not isinstance(leg, dict):
        return False, "malformed leg"
    if leg.get("invalid_reason"):
        return False, leg["invalid_reason"]
    if not leg.get("input_staged") and not leg.get("transfer_note"):
        return False, ("no input_staged stamp or transfer_note: cannot "
                       "rule out tunnel-transfer-bound timing")
    if name == "resnet50" and \
            leg.get("mfu_convention") != RESNET_MFU_CONVENTION:
        return False, ("mfu_convention %r != %d: pre-convention-fix MFU "
                       "understates 2x" % (leg.get("mfu_convention"),
                                           RESNET_MFU_CONVENTION))
    cache_stamp_keys = {"decode": "per_token_s",
                        "decode_ssm": "per_token_s",
                        "serving": "ttft_p50_s",
                        "serving_faults": "recovery_wall_s",
                        "serving_restart": "restore_rto_s",
                        "serving_prefix": "ttft_p50_s",
                        "serving_overload": "ttft_p99_high_s",
                        "serving_sharded": "tokens_per_sec",
                        "serving_disagg": "ttft_p95_s",
                        "serving_fleet": "tokens_per_sec",
                        "serving_lora": "tokens_per_sec",
                        "speculative": "tokens_per_sec"}
    if name in cache_stamp_keys:
        # a decode/serving/speculative number without its cache-layout
        # AND cache-dtype stamps cannot say whether it measured the
        # dense or the paged path (they differ in reachable HBM by up
        # to max_len/actual-tokens) or the fp32 or int8 cache (~4x
        # fewer bytes streamed per step): unpromotable.  Timed sub-legs
        # are identified by their timing stamp: marginal per-token time
        # for decode, TTFT for serving, tokens/s for speculative.
        stamp = cache_stamp_keys[name]
        timed = {k: v for k, v in leg.items()
                 if isinstance(v, dict) and stamp in v}
        missing = sorted(k for k, v in timed.items()
                         if not v.get("cache_layout")
                         or not v.get("cache_dtype"))
        if not timed or missing:
            return False, ("%s leg missing cache_layout/cache_dtype on "
                           "%s: dense-vs-paged / fp32-vs-int8 "
                           "provenance unknown"
                           % (name, missing or "every timed sub-leg"))
        # a KERNEL-ROUTED number (decode_route == "pallas", the fused
        # §5l kernel) without its bandwidth-utilization stamp (tok/s x
        # compiler-stated bytes/token) cannot say what fraction of the
        # streamed HBM bytes the kernel sustained — the roofline figure
        # the kernel exists to move, so it is the number's provenance
        unstamped = sorted(
            k for k, v in timed.items()
            if v.get("decode_route") == "pallas"
            and not isinstance(v.get("bandwidth_util_bytes_per_sec"),
                               (int, float)))
        if unstamped:
            return False, ("%s leg kernel-routed (decode_route=pallas) "
                           "but missing bandwidth_util_bytes_per_sec "
                           "on %s: a fused-kernel number must carry "
                           "the sustained-bandwidth stamp it exists "
                           "to improve" % (name, unstamped))
        if name == "decode_ssm":
            # an O(1)-cache tokens/s without its NUMERIC capacity stamp
            # (slots per GB of HBM at constant per-slot state) cannot
            # say what the model class bought over positional K/V — the
            # capacity figure IS the number's provenance (§5p)
            uncapped = sorted(
                k for k, v in timed.items()
                if not isinstance(v.get("slots_per_gb"), (int, float))
                or isinstance(v.get("slots_per_gb"), bool))
            if uncapped:
                return False, ("decode_ssm leg missing numeric "
                               "slots_per_gb on %s: an O(1)-cache "
                               "number must carry the capacity stamp "
                               "it exists to improve" % (uncapped,))
        if name == "serving_faults":
            # a recovery wall time whose survivors LOST tokens measured
            # a broken recovery, not a working one: greedy survivors are
            # token-identical by contract, so tokens_lost != 0 makes the
            # number structurally unpromotable
            lossy = sorted(k for k, v in timed.items()
                           if v.get("tokens_lost", 1) != 0)
            if lossy:
                return False, ("serving_faults leg lost tokens on %s: "
                               "greedy survivors must be byte-identical "
                               "to the fault-free run" % (lossy,))
        if name == "serving_restart":
            # a restore RTO whose survivors LOST tokens measured a
            # broken journal replay (byte-identity is the §5m
            # contract), and one that replayed NO requests measured
            # file I/O over an empty journal — both structurally
            # unpromotable
            lossy = sorted(k for k, v in timed.items()
                           if v.get("tokens_lost", 1) != 0)
            if lossy:
                return False, ("serving_restart leg lost tokens on "
                               "%s: restored greedy requests must be "
                               "byte-identical to the uninterrupted "
                               "run" % (lossy,))
            unreplayed = sorted(k for k, v in timed.items()
                                if not v.get("requests_replayed"))
            if unreplayed:
                return False, ("serving_restart leg replayed no "
                               "requests on %s: an RTO over an empty "
                               "journal measured file I/O, not "
                               "recovery" % (unreplayed,))
        if name == "speculative":
            # a speculative tokens/s additionally needs its
            # acceptance_rate stamp: without it the number cannot say
            # whether it measured a draft that mostly landed or mostly
            # wasted work — the rate IS the number's provenance (the
            # plain_* baseline sub-leg is exempt: it drafts nothing)
            no_rate = sorted(k for k, v in timed.items()
                             if not k.startswith("plain")
                             and "acceptance_rate" not in v)
            if no_rate:
                return False, ("speculative leg missing acceptance_rate "
                               "on %s: cannot tell a measured draft win "
                               "from wasted drafting" % (no_rate,))
        if name == "serving_prefix":
            # a prefix-sharing number whose sharing-on sub-leg cannot
            # say whether the index actually FIRED (no hit-rate stamp)
            # measured chunked prefill at best and nothing at worst;
            # the off sub-leg is exempt — its index is disabled by
            # construction, its hit rate is definitionally 0
            unhit = sorted(k for k, v in timed.items()
                           if not k.startswith("sharing_off")
                           and v.get("prefix_hit_rate") is None)
            if unhit:
                return False, ("serving_prefix leg missing "
                               "prefix_hit_rate on %s: cannot tell a "
                               "measured sharing win from plain "
                               "chunked prefill" % (unhit,))
        if name == "serving_overload":
            # a closed-loop claim needs the loop's own evidence: the
            # degraded sub-leg must say what the ladder DID (preempt/
            # resume counts) and both sub-legs must carry the SLO
            # plane's burn stamp — a "degradation helped" number that
            # cannot show a preemption or a burn reading measured the
            # traffic generator, not the scheduler
            unproven = sorted(
                k for k, v in timed.items()
                if not k.startswith("degrade_off")
                and ("preemptions" not in v or "resumes" not in v
                     or "spill_bytes_total" not in v))
            if unproven:
                return False, ("serving_overload leg missing preempt/"
                               "resume/spill stamps on %s: cannot tell "
                               "a measured ladder win from plain "
                               "priority luck" % (unproven,))
            unburned = sorted(k for k, v in timed.items()
                              if "slo_ttft_burn_slow_max" not in v)
            if unburned:
                return False, ("serving_overload leg missing the "
                               "slo_ttft_burn_slow_max stamp on %s: "
                               "the closed-loop claim needs the SLO "
                               "plane's own reading" % (unburned,))
        if name == "serving_sharded":
            # a "sharded" record with no sharded mesh sub-leg measured
            # nothing this leg exists to measure (a 1-device run skips
            # every dp×mp>1 mesh): unpromotable, never a silent
            # baseline-only pass
            if not any(k != "mesh_1x1" for k in timed):
                return False, ("serving_sharded leg has no sharded "
                               "mesh sub-leg (only the unsharded "
                               "baseline ran — not enough devices?): "
                               "a sharded record must measure at "
                               "least one dp*mp>1 mesh")
            # a sharded tok/s without its measured-vs-ideal scaling
            # stamp and the per-shard compiler cost stamps cannot say
            # whether sharding bought anything or what one shard asks
            # of its chip — the whole point of the leg; the unsharded
            # mesh_1x1 baseline is exempt (its scaling is the
            # definition of 1.0 and its costs are the whole-pool ones
            # the plain serving leg already gates)
            unscaled = sorted(
                k for k, v in timed.items()
                if k != "mesh_1x1"
                and (v.get("scaling_efficiency") is None
                     or v.get("cost_flops_per_shard") is None
                     or v.get("cost_bytes_per_shard") is None
                     or v.get("cost_hbm_reserved_per_shard") is None
                     or v.get("kv_resident_bytes_per_shard") is None))
            if unscaled:
                return False, ("serving_sharded leg missing scaling_"
                               "efficiency or per-shard cost/HBM "
                               "stamps on %s: a sharded number must "
                               "carry its measured-vs-ideal scaling "
                               "and what one shard asks of its chip"
                               % (unscaled,))
            # a QUANTIZED-collective sub-leg (§5r) without its NUMERIC
            # traced-shape wire-byte stamp cannot say what the
            # quantization bought over the dense ring — the byte
            # column IS the number's provenance (off-TPU the emulated
            # mesh's tok/s certainly can't say it)
            unquant = sorted(
                k for k, v in timed.items()
                if v.get("collective_quant") not in (None, "none")
                and (not isinstance(v.get("collective_bytes_per_token"),
                                    (int, float))
                     or isinstance(v.get("collective_bytes_per_token"),
                                   bool)))
            if unquant:
                return False, ("serving_sharded leg missing numeric "
                               "collective_bytes_per_token on "
                               "quantized sub-legs %s: a quantized-"
                               "collective number must carry the "
                               "traced wire-byte stamp it exists to "
                               "shrink" % (unquant,))
        if name == "serving_disagg":
            # the tier split's headline IS the fused-vs-disagg
            # comparison: a record missing either improvement column
            # compared nothing (the sub-leg that failed took the
            # comparison with it); a lossy hand-off broke the
            # byte-identity contract (a hand-off may move computation,
            # never change tokens); and a record whose hand-off never
            # fired measured two idle engines wearing the tier roles
            if not isinstance(leg.get("ttft_p95_improvement_pct"),
                              (int, float)) \
                    or not isinstance(leg.get("itl_p95_improvement_pct"),
                                      (int, float)):
                return False, ("serving_disagg leg missing the "
                               "ttft/itl p95 improvement stamps: a "
                               "disaggregation number that cannot "
                               "compare against the fused engine on "
                               "the same traffic claims nothing")
            if leg.get("tokens_lost", 1) != 0:
                return False, ("serving_disagg leg lost tokens vs the "
                               "fused reference: a hand-off can move "
                               "computation between tiers, never "
                               "change greedy tokens")
            if not leg.get("kv_transfers"):
                return False, ("serving_disagg leg recorded no K/V "
                               "hand-offs: without a transfer the "
                               "pair measured two idle engines, not "
                               "disaggregation")
        if name == "serving_fleet":
            # the fleet's headline IS the multi-engine comparison: a
            # multi-engine sub-leg without its measured-vs-ideal
            # scaling stamp compared nothing; a chaos sub-leg without
            # its migration RTO (or with token loss) measured a fleet
            # that cannot survive the one event the tier exists to
            # survive; and ANY lost token breaks the routing/migration
            # byte-identity contract
            unscaled = sorted(
                k for k, v in timed.items()
                if k.startswith("engines_") and k != "engines_1"
                and not isinstance(v.get("scaling_efficiency"),
                                   (int, float)))
            if unscaled:
                return False, ("serving_fleet leg missing "
                               "scaling_efficiency on %s: a "
                               "multi-engine number must carry its "
                               "measured-vs-ideal scaling" % (unscaled,))
            chaos = leg.get("chaos")
            if not isinstance(chaos, dict) \
                    or not isinstance(chaos.get("migration_rto_s"),
                                      (int, float)):
                return False, ("serving_fleet leg missing the chaos "
                               "sub-leg's migration_rto_s stamp: a "
                               "fleet record must measure the "
                               "engine-death recovery it exists for")
            if not chaos.get("requests_migrated"):
                return False, ("serving_fleet chaos sub-leg migrated "
                               "no requests: killing an idle engine "
                               "measured nothing")
            if leg.get("tokens_lost", 1) != 0:
                return False, ("serving_fleet leg lost tokens vs the "
                               "1-engine reference: routing and "
                               "migration move computation between "
                               "engines, never change greedy tokens")
            if leg.get("prefix_affinity_hit_rate") is None:
                return False, ("serving_fleet leg missing "
                               "prefix_affinity_hit_rate: cannot tell "
                               "an affinity-routed fleet from N "
                               "independent caches")
        if name == "serving_lora":
            # the multi-LoRA headline IS the shared-bank-vs-dedicated
            # comparison under the as-data contract: a timed sub-leg
            # that cannot say how many adapters it served compared
            # nothing; a sub-leg that compiled during traffic (or
            # whose cost fingerprint moved) broke the exactly-two
            # contract the leg exists to demonstrate; a lossy record
            # broke the bank's byte-identity contract; and a hot-load
            # that compiled measured refresh_weights-by-retrace, not
            # a hot swap
            unadapted = sorted(
                k for k, v in timed.items()
                if not isinstance(v.get("adapters"), (int, float))
                or isinstance(v.get("adapters"), bool))
            if unadapted:
                return False, ("serving_lora leg missing the numeric "
                               "adapters stamp on %s: a multi-LoRA "
                               "number that cannot say how many "
                               "fine-tunes it mixed claims nothing"
                               % (unadapted,))
            recompiled = sorted(
                k for k, v in timed.items()
                if v.get("compiles_during_traffic", 1) != 0
                or v.get("cost_version_changed", True))
            if recompiled:
                return False, ("serving_lora leg compiled (or moved "
                               "cost_version) during traffic on %s: "
                               "adapter ids and sampling are traced "
                               "data — the exactly-two contract allows "
                               "ZERO new executables" % (recompiled,))
            if leg.get("tokens_lost", 1) != 0:
                return False, ("serving_lora leg lost tokens vs the "
                               "dedicated single-adapter engines: the "
                               "stacked bank moves the delta math, "
                               "never the tokens")
            if leg.get("hot_load_compiles", 1) != 0:
                return False, ("serving_lora leg's load_adapter "
                               "compiled: a hot swap is a bank-row "
                               "device write, never a retrace")
        if name == "serving":
            # the §5g tracing contract is that the flight recorder is
            # effectively free on the tick path; a serving number whose
            # measured tracing-on overhead exceeds 3% was taken on an
            # engine where the recorder IS part of the cost, and must
            # not be presented as the scheduler's price (legacy records
            # without the stamp predate tracing and stand as-is)
            pct = leg.get("trace_overhead_pct")
            if pct is not None and pct > 3.0:
                return False, ("serving leg trace overhead %.3g%% > 3%%: "
                               "tracing must be hot-path-free — this "
                               "number measured the recorder, not the "
                               "scheduler" % (pct,))
    return True, ""


def _promote_stored_legs(stored):
    """(legs, rejected) for the fallback output, gated by
    _leg_promotable: a leg that fails the gate lands in ``rejected``
    (name -> reason) instead of being presented as a healthy number.
    Legacy-shape records (legs at top level) carry metadata strings
    alongside the leg dicts; only dict values are legs."""
    raw = (stored or {}).get("legs") or stored or {}
    legs, rejected = {}, {}
    for name, leg in raw.items():
        if not isinstance(leg, dict):
            continue  # legacy-shape metadata (measured_at/note/...)
        ok, reason = _leg_promotable(name, leg)
        if ok:
            legs[name] = leg
        else:
            rejected[name] = reason
    return legs, rejected


def main():
    """Watchdog wrapper: the measurement phase runs in a child process.

    A tunnel that dies MID-measurement leaves jax blocked in an
    uninterruptible transport call — no exception, no output, and the
    round's evidence would be lost. The parent holds the chip lock (so
    lock contention never eats the child's budget), waits
    ``BENCH_TIMEOUT_S`` (default 2400s) for the measurement itself, then
    kills the child's process group and emits the last VERIFIED on-chip
    record instead (the same promotion a clean CPU fallback does).
    """
    if os.environ.get("_BENCH_SHARDED_CHILD") == "1":
        # checked FIRST: the sharded child inherits _BENCH_CHILD=1 when
        # the watchdog's measurement child spawned it
        _sharded_bench_child()
        return
    if os.environ.get("_BENCH_CHILD") == "1":
        _measure_and_print()
        return
    import signal
    import subprocess
    import sys

    timeout_s = float(os.environ.get("BENCH_TIMEOUT_S", "2400"))
    env = dict(os.environ, _BENCH_CHILD="1")
    lock_fd = None
    if env.get("JAX_PLATFORMS") != "cpu":
        # lock in the PARENT: a contended lock then costs wall-clock
        # before the child's measurement budget starts, not inside it
        lock_fd = _acquire_chip_lock()
        if lock_fd is None or not _probe_accelerator():
            # no lock or dead tunnel: measure on CPU and don't sit on the
            # lock while doing it
            env["JAX_PLATFORMS"] = "cpu"
            if lock_fd is not None:
                os.close(lock_fd)
                lock_fd = None
        else:
            env["_BENCH_LOCK_HELD"] = "1"
    reason = None
    proc = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__)], env=env,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        start_new_session=True)  # own group: killpg reaches grandchildren
    try:
        out, err = proc.communicate(timeout=timeout_s)
    except subprocess.TimeoutExpired:
        reason = ("measurement timed out after %ds - axon transport hang; "
                  "child process group killed" % timeout_s)
        try:
            os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
        except OSError:
            pass
        try:
            # bounded reap: a D-state child that cannot die must not hang
            # the watchdog too — fall through and emit the stored record
            out, err = proc.communicate(timeout=15)
        except Exception:  # noqa: BLE001
            out, err = "", ""
    if err:
        sys.stderr.write(err[-4000:])  # keep leg tracebacks debuggable
    result = None
    for l in (out or "").strip().splitlines():
        if not l.startswith("{"):
            continue
        try:  # must be OUR result line, not a stray/truncated dict print
            parsed = json.loads(l)
        except ValueError:
            continue
        if isinstance(parsed, dict) and "metric" in parsed:
            result = l
    if result is not None:
        # the child's final JSON is the result — accept it even if the
        # process then died/hung in transport teardown
        print(result)
        return
    if reason is None:
        reason = "measurement child exited %d with no JSON" \
            % proc.returncode

    stored, stored_bert, bert_rejected = _stored_bert()
    if stored_bert:
        legs, rejected = _promote_stored_legs(stored)
        print(json.dumps(_primary(stored_bert, {
            "backend": "tpu (stored)",
            "provenance": "last_verified_tpu_watchdog",
            "watchdog_reason": reason,
            "measured_at": (stored or {}).get("measured_at"),
            "git_rev": (stored or {}).get("git_rev"),
            "stored_legs": legs,
            "rejected_stored_legs": rejected or None,
            "stored_note": (stored or {}).get("note"),
        })))
    else:
        print(json.dumps({
            "metric": "bert_base_tokens_per_sec_per_chip",
            "value": 0.0, "unit": "tokens/s", "vs_baseline": 0.0,
            "extra": {"provenance": "watchdog_no_stored_record",
                      "bert_rejected_reason": bert_rejected,
                      "watchdog_reason": reason}}))


def _measure_and_print():
    lock_fd = None
    if os.environ.get("JAX_PLATFORMS") != "cpu" \
            and os.environ.get("_BENCH_LOCK_HELD") != "1":
        lock_fd = _acquire_chip_lock()
        if lock_fd is None:
            # someone else holds the chip past the timeout: NEVER run on
            # the accelerator unlocked (two processes on one chip is what
            # wedged the round-3 tunnel) — degrade to CPU
            os.environ["JAX_PLATFORMS"] = "cpu"
    if os.environ.get("JAX_PLATFORMS") != "cpu":
        if not _probe_accelerator():
            os.environ["JAX_PLATFORMS"] = "cpu"
            if lock_fd is not None:  # not using the chip: free it now
                os.close(lock_fd)
                lock_fd = None

    import jax

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        # pre-registered accelerator plugins ignore the env var; force it
        jax.config.update("jax_platforms", "cpu")

    import paddle_tpu as pt

    on_tpu = jax.default_backend() not in ("cpu",)
    legs = {}
    errors = {}
    for name, fn in (("bert", bench_bert), ("resnet50", bench_resnet50),
                     ("mnist_lenet", bench_mnist),
                     ("ernie_sharding", bench_ernie_sharding),
                     ("gpt_pp_mp", bench_gpt_block),
                     ("longseq_flash_8k", bench_longseq_flash),
                     ("bert_k8_multistep", bench_bert_multistep),
                     ("mnist_k32_multistep", bench_mnist_multistep),
                     ("decode", bench_decode),
                     ("decode_ssm", bench_decode_ssm),
                     ("serving", bench_serving),
                     ("serving_faults", bench_serving_faults),
                     ("serving_restart", bench_serving_restart),
                     ("serving_prefix", bench_serving_prefix),
                     ("serving_overload", bench_serving_overload),
                     ("serving_sharded", bench_serving_sharded),
                     ("serving_disagg", bench_serving_disagg),
                     ("serving_fleet", bench_serving_fleet),
                     ("serving_lora", bench_serving_lora),
                     ("speculative", bench_speculative)):
        try:
            legs[name] = fn(pt, jax, on_tpu)
        except Exception as e:  # noqa: BLE001 - keep remaining legs alive
            errors[name] = str(e)[:200]

    if on_tpu and legs:
        # verified on-chip run (any leg): persist it so later CPU fallbacks
        # can promote it (with provenance) instead of zeroing out the round.
        # If bert failed on-chip, keep the previous record's bert leg so the
        # primary metric never regresses to nothing.
        now, rev = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()), _git_rev()
        prev = _load_tpu_record() or {}
        # each leg carries its own provenance so an inherited leg is never
        # re-stamped with a rev/timestamp at which it did not actually run;
        # input_staged stays literal truth: _time_steps device_puts args
        # before the clock starts, so legs default to staged — but a leg
        # that declares its own value (the decode leg uploads prompts
        # inside the timed region and relies on transfer_note) keeps it
        fresh = {k: dict(v, measured_at=now, git_rev=rev,
                         input_staged=v.get("input_staged", True))
                 for k, v in legs.items()}
        merged = dict((prev.get("legs") or {}), **fresh)
        if "bert" not in merged and prev.get("bert"):
            merged["bert"] = dict(prev["bert"],  # legacy record shape
                                  measured_at=prev.get("measured_at"),
                                  git_rev=prev.get("git_rev"))
        record = _round_tree({
            "measured_at": now,
            "git_rev": rev,
            "backend": "tpu (%s)" % jax.devices()[0].device_kind,
            "legs": merged,
            "leg_errors": errors or None,
        })
        _persist_tpu_record(record)

    if on_tpu and "bert" in legs:
        out = _primary(legs["bert"], {
            "backend": jax.default_backend(), "provenance": "live",
            "legs": legs, "leg_errors": errors or None})
    else:
        # tunnel down (or a bert failure on-chip): promote the most recent
        # VERIFIED on-chip measurement as the primary metric; this run's
        # legs are attached subordinate with their true backend label.
        stored, stored_bert, bert_rejected = _stored_bert()
        this_run = {"backend": jax.default_backend(), "legs": legs,
                    "leg_errors": errors or None}
        if stored_bert:
            promoted, rejected = _promote_stored_legs(stored)
            out = _primary(stored_bert, {
                "backend": "tpu (stored)",
                "provenance": "last_verified_tpu",
                "measured_at": stored.get("measured_at"),
                "git_rev": stored.get("git_rev"),
                "stored_legs": promoted,
                "rejected_stored_legs": rejected or None,
                "stored_note": stored.get("note"),
                "this_run": this_run})
        elif "bert" in legs:
            out = _primary(legs["bert"], dict(
                this_run, provenance="no_stored_tpu_record",
                bert_rejected_reason=bert_rejected))
        else:
            out = {"metric": "bert_base_tokens_per_sec_per_chip",
                   "value": 0.0, "unit": "tokens/s", "vs_baseline": 0.0,
                   "extra": _round_tree(dict(
                       this_run, provenance="bert_leg_failed_no_record"))}
    print(json.dumps(out))


if __name__ == "__main__":
    main()
