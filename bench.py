"""Benchmark harness: both BASELINE.md headline metrics on one chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "extra"}.

Workloads:
- **BERT-base pretrain** (BASELINE.md config #3, seq 512) through the
  fully-jitted TrainStep (forward + backward + AdamW, donated buffers) —
  the primary metric (tokens/s/chip).
- **ResNet50 ImageNet** (BASELINE.md config #2: compiled path + AMP) —
  reported in ``extra`` as imgs/sec/chip with its own MFU.

The reference publishes no absolute numbers (BASELINE.md: "published: {}"),
so ``vs_baseline`` reports measured model FLOPs utilization (MFU) against
the 0.40 A100-class MFU target named in BASELINE.md's north star.
"""
from __future__ import annotations

import json
import time

import numpy as np

# ResNet50 ImageNet-224 analytic forward FLOPs per image (multiply+add = 2
# FLOPs; conv+fc, the standard 4.09 GFLOP figure); backward ~= 2x forward.
RESNET50_FWD_FLOPS = 4.089e9


def _peak_flops(jax, on_tpu: bool) -> float:
    """Per-chip bf16 peak FLOP/s by device generation (MFU convention)."""
    kind = jax.devices()[0].device_kind.lower() if on_tpu else "cpu"
    if "v5 lite" in kind or "v5e" in kind:
        return 197e12
    if "v5p" in kind or "v5" in kind:
        return 459e12
    if "v4" in kind:
        return 275e12
    if "v6" in kind or "trillium" in kind:
        return 918e12
    return 197e12 if on_tpu else 1e12


def _sweep_best(batches, run_leg):
    """Run ``run_leg(batch) -> result`` per batch, keep the best throughput
    (key "_tps"); a leg that raises (HBM OOM at the spill boundary) is
    skipped so the surviving measurements still produce the metric."""
    best = None
    errors = []
    for batch in batches:
        try:
            cur = run_leg(batch)
        except Exception as e:  # noqa: BLE001 - resource exhaustion etc.
            errors.append("batch %s: %s" % (batch, str(e)[:120]))
            continue
        if best is None or cur["_tps"] > best["_tps"]:
            best = cur
    if best is None:
        raise RuntimeError("every sweep leg failed: %s" % "; ".join(errors))
    best.pop("_tps", None)
    return best


def _time_steps(step, args, iters: int) -> float:
    for _ in range(2):  # warmup (includes compile)
        loss = step(*args)
    float(loss)
    t0 = time.perf_counter()
    for _ in range(iters):
        loss = step(*args)
    float(loss)  # block on the last step
    return (time.perf_counter() - t0) / iters, float(loss)


def bench_bert(pt, jax, on_tpu: bool):
    from paddle_tpu.jit import TrainStep
    from paddle_tpu.models import (TransformerLM, TransformerLMCriterion,
                                   bert_base_config)

    pt.seed(0)
    cfg = bert_base_config()
    if not on_tpu:  # CPU smoke: shrink so the harness itself stays testable
        cfg.update(num_layers=2, hidden_size=128, num_heads=2,
                   intermediate_size=512, vocab_size=1024)
    # batch 40 was the measured v5e knee (0.4365 MFU); sweep its
    # neighborhood in case layout/memory behavior moved
    batches, seq = ([40, 48, 32], 512) if on_tpu else ([2], 128)

    model = TransformerLM(**cfg, dropout=0.0)
    criterion = TransformerLMCriterion(shift_labels=False)
    opt = pt.optimizer.AdamW(1e-4, parameters=model.parameters())
    # bf16 mixed precision: params/activations in bf16 (MXU native), fp32
    # master weights in the optimizer, loss math fp32 via the amp black list
    model, opt = pt.amp.decorate(model, opt, level="O2", dtype="bfloat16")

    def loss_fn(m, ids, labels):
        with pt.amp.auto_cast(level="O1", dtype="bfloat16"):
            return criterion(m(ids), labels)

    step = TrainStep(model, loss_fn, opt)
    rng = np.random.RandomState(0)

    def leg(batch):
        ids = rng.randint(0, cfg["vocab_size"], (batch, seq)).astype("int32")
        dt, loss = _time_steps(step, (ids, ids), 10 if on_tpu else 3)
        tps = batch * seq / dt
        flops_per_step = model.flops_per_token(seq) * batch * seq
        return {
            "_tps": tps,
            "tokens_per_sec": tps,
            "step_time_s": dt,
            "mfu": flops_per_step / dt / _peak_flops(jax, on_tpu),
            "batch": batch,
            "seq": seq,
            "loss": loss,
        }

    return _sweep_best(batches, leg)


def bench_resnet50(pt, jax, on_tpu: bool):
    """Config #2: ResNet50, compiled ("static Executor") path + AMP.

    Batch size is swept (per-chip HBM sets the throughput knee; a spilling
    batch collapses per-image speed — measured 6.6s/step at 256 vs
    0.065s/step at 64 on v5e) and the best imgs/sec leg wins; a leg that
    OOMs is skipped by _sweep_best.
    """
    from paddle_tpu.jit import TrainStep
    from paddle_tpu.vision.models import resnet50

    pt.seed(0)
    if on_tpu:
        batches, hw, classes = [64, 128, 256], 224, 1000
        flops_fwd = RESNET50_FWD_FLOPS
    else:
        batches, hw, classes = [4], 32, 10
        flops_fwd = 1e9  # nominal; CPU smoke only checks the harness runs

    model = resnet50(num_classes=classes)
    criterion = pt.nn.CrossEntropyLoss()
    opt = pt.optimizer.Momentum(0.1, parameters=model.parameters())
    model, opt = pt.amp.decorate(model, opt, level="O2", dtype="bfloat16")

    def loss_fn(m, x, y):
        with pt.amp.auto_cast(level="O1", dtype="bfloat16"):
            return criterion(m(x), y)

    step = TrainStep(model, loss_fn, opt)  # donated buffers: less HBM
    rng = np.random.RandomState(0)

    def leg(batch):
        imgs = rng.randn(batch, 3, hw, hw).astype("float32")
        labels = rng.randint(0, classes, (batch,)).astype("int64")
        dt, loss = _time_steps(step, (imgs, labels), 6 if on_tpu else 2)
        ips = batch / dt
        flops_per_step = 3.0 * flops_fwd * batch  # fwd + ~2x bwd
        return {
            "_tps": ips,
            "imgs_per_sec": ips,
            "step_time_s": dt,
            "mfu": flops_per_step / dt / _peak_flops(jax, on_tpu),
            "batch": batch,
            "loss": loss,
        }

    return _sweep_best(batches, leg)


def _probe_accelerator(timeout_s: int = 180) -> bool:
    """Check from a THROWAWAY subprocess that the accelerator runtime
    answers; a wedged tunnel (the axon transport can hang for hours) must
    not hang the bench — we fall back to CPU and still emit the JSON line."""
    import subprocess
    import sys

    try:
        proc = subprocess.run(
            [sys.executable, "-c",
             "import jax; d=jax.devices(); print(d[0].platform)"],
            capture_output=True, text=True, timeout=timeout_s)
        return proc.returncode == 0 and "cpu" not in proc.stdout
    except subprocess.TimeoutExpired:
        return False


def main():
    import os

    if os.environ.get("JAX_PLATFORMS") != "cpu" and not _probe_accelerator():
        os.environ["JAX_PLATFORMS"] = "cpu"

    import jax

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        # pre-registered accelerator plugins ignore the env var; force it
        jax.config.update("jax_platforms", "cpu")

    import paddle_tpu as pt

    on_tpu = jax.default_backend() not in ("cpu",)
    bert = bench_bert(pt, jax, on_tpu)
    last_tpu = None
    if not on_tpu:
        # accelerator unreachable: attach the last recorded on-chip numbers
        # so the CPU fallback is not mistaken for a perf regression
        try:
            with open(os.path.join(os.path.dirname(
                    os.path.abspath(__file__)), "TPU_MEASUREMENT.json")) as f:
                last_tpu = json.load(f)
        except Exception:
            last_tpu = None
    try:
        resnet = bench_resnet50(pt, jax, on_tpu)
    except Exception as e:  # keep the primary metric alive
        resnet = {"error": str(e)[:200]}

    print(json.dumps({
        "metric": "bert_base_tokens_per_sec_per_chip",
        "value": round(bert["tokens_per_sec"], 1),
        "unit": "tokens/s",
        "vs_baseline": round(bert["mfu"] / 0.40, 4),
        "extra": {
            "step_time_s": round(bert["step_time_s"], 4),
            "mfu": round(bert["mfu"], 4),
            "batch": bert["batch"],
            "seq": bert["seq"],
            "backend": jax.default_backend(),
            "loss": bert["loss"],
            "last_tpu_measurement": last_tpu,
            "resnet50": {
                k: (round(v, 4) if isinstance(v, float) else v)
                for k, v in resnet.items()
            },
        },
    }))


if __name__ == "__main__":
    main()
